"""The replicated fleet scheduler: macro-rounds over writer groups.

``ReplicatedScheduler`` is the :class:`serve.scheduler.FleetScheduler`
with one substitution: **delivery is owned by the broadcast bus**.  A
replica's stream is the group's full assembled op sequence (shared
arrays), but the scheduler may only stage ops up to the replica's
sequence-keyed assembled prefix — the bus's delivery point — so a
partitioned or lagging replica simply waits while its writer-group
peers keep serving, and catches up when the backlog flushes.  Remote
(peer-authored) ops reach the device through the SAME macro dispatch as
local ones — the batched downstream merge happens inside the macro
scan (``engine/merge_fleet.py merge_rows_body`` for the scan kernel,
its parity-pinned fused twin otherwise), so remote-apply stays
device-resident and never adds a sync boundary: the bus is pure host
bookkeeping inside the sanitized hot scope.

Everything else — capacity classes, promotion, eviction/restore through
the checkpoint spool, the WAL, snapshot barriers, chaos recovery,
degradation — applies to replica rows unchanged: **replica rows are
pool rows**.  The scheduler adds the replication telemetry on top:
per-class remote-merge counters, the divergence-depth gauge, broadcast
fan-out accounting (``obs/shard.py ReplicaMetrics``), and the two
replication chaos hooks (``replica_partition`` / ``merge_reorder``).
"""

from __future__ import annotations

import numpy as np

from ...obs.shard import ReplicaMetrics
from ..scheduler import FleetScheduler, _Plan
from .broadcast import BroadcastBus
from .group import GroupTable, attach_turn_blocks

#: Idle-round safety bound: the planner advances the round clock while
#: waiting on bus delivery (partition spans, in-flight lag); a backlog
#: that never drains within this many consecutive idle rounds is a bug,
#: not a wait.
IDLE_ROUND_LIMIT = 100_000

#: Default partition span (rounds until heal) when the fault event
#: carries no explicit ``param``.
DEFAULT_PARTITION_SPAN = 3


class ReplicatedScheduler(FleetScheduler):
    def __init__(
        self,
        pool,
        streams,
        table: GroupTable,
        *,
        turn_ops: int = 64,
        pub_ops: int | None = None,
        remote_lag: int = 1,
        history_sample: int = 16,
        seed: int = 0,
        **kw,
    ):
        super().__init__(pool, streams, **kw)
        self.table = table
        self.turn_ops = turn_ops
        attach_turn_blocks(table, streams, turn_ops)
        # RA-checker history sampling: a seeded spread over the logical
        # docs (recording every group's history would hold one event
        # per delivered block per replica — sampled is the contract)
        gids = sorted(g.logical_id for g in table)
        rng = np.random.default_rng(seed + 2)
        n_hist = min(history_sample, len(gids))
        sample = {
            int(g) for g in rng.choice(gids, size=n_hist, replace=False)
        } if n_hist else set()
        self.replica_metrics = ReplicaMetrics(
            self.stats.metrics, pool.classes
        )
        self.bus = BroadcastBus(
            table,
            pub_ops=pub_ops or self.batch * self.macro_k,
            op_nbytes=sum(dt.itemsize for dt in pool.op_dtypes),
            remote_lag=remote_lag,
            journal=self.journal,
            metrics=self.replica_metrics,
            history_groups=sample,
        )
        # bus-owned delivery: every replica starts with an empty
        # assembled prefix, whatever queue_cap said
        for st in streams.values():
            st.delivered = 0
        self.merged_ops = 0
        self.merged_unit_ops = 0
        self.local_ops = 0
        self._idle_rounds = 0

    # ---- bus integration ----

    def _fire_replication_faults(self) -> None:
        """Poll the two replication chaos hooks at the bus tick (the
        same fixed-point discipline as the other injector hooks)."""
        ev = self.faults.partition_event(self.round)
        if ev is not None:
            targets = self.bus.live_partition_targets()
            if targets:
                gid, w = targets[
                    self.faults.pick(list(range(len(targets))))
                ]
                span = ev.param or DEFAULT_PARTITION_SPAN
                heal = self.round + span
                self.bus.start_partition(gid, w, heal, event=ev)
                ev.fire(self.round, group=gid, writer=w,
                        heal_round=heal)
                self.stats.faults_injected += 1
                self._note_fault()
        ev = self.faults.reorder_event(self.round)
        if ev is not None and self.bus._reorder is None:
            # armed now, fires at the next tick that actually delivers
            # remote batches (the permutation needs traffic to permute)
            self.bus.arm_reorder(self.faults.rng, ev)
            self.stats.faults_injected += 1
            self._note_fault()

    def _deliver(self, st) -> None:
        """Bus-owned delivery: the replica's schedulable window is its
        assembled broadcast prefix (monotone by construction)."""
        got = self.bus.delivered_ops(st.doc_id)
        if st.delivered is None or got > st.delivered:
            st.delivered = got

    def _plan(self) -> _Plan | None:
        """The base planner with the bus tick folded into the round
        loop: publish/deliver for this round, select, and — when no
        lane could be staged — advance the clock over arrival gaps AND
        bus waits (in-flight deliveries, partition spans)."""
        while True:
            self._k_round = self.effective_k
            self._planned_degraded = self._degrade_left > 0
            if self.faults is not None:
                self._fire_replication_faults()
            self.bus.tick(self.round)
            plan = _Plan(base_round=self.round)
            self._select(plan)
            if plan.lanes:
                self._idle_rounds = 0
                self._place(plan)
                return plan
            pending = [
                s.arrival for s in self.streams.values()
                if s.remaining and s.arrival > self.round
            ]
            if pending:
                self.round = min(pending)
                continue
            if self.bus.pending_work():
                self._idle_rounds += 1
                if self._idle_rounds > IDLE_ROUND_LIMIT:
                    raise RuntimeError(
                        "replicated scheduler: broadcast backlog never "
                        f"drained after {IDLE_ROUND_LIMIT} idle rounds"
                    )
                self.round += 1
                continue
            return None

    def _advance(self, plan: _Plan) -> None:
        """Remote-merge attribution BEFORE the base class advances the
        cursors: every staged slice's ops split into the writer's own
        (upstream) share and the peers' broadcast (downstream-merge)
        share, counted under the landing capacity class."""
        traced = self.reqtrace.armed
        for cls, lanes in plan.lanes.items():
            for lane in lanes:
                st = lane.stream
                if st.doc_id in self._dead_lanes:
                    continue
                g, w = self.table.group_of(st.doc_id)
                rem_ops = 0
                rem_units = 0
                by_writer: dict[int, int] | None = {} if traced else None
                # ONE block walk per lane: interval sums and (armed
                # only) per-writer attribution both fall out of
                # _remote_segments (the coalesced remote_intervals
                # view is for callers that need the interval list)
                for a, b, ow in g._remote_segments(w, st.cursor,
                                                   lane.end):
                    rem_ops += b - a
                    rem_units += (
                        st.units_before(b) - st.units_before(a)
                    )
                    if by_writer is not None:
                        by_writer[ow] = by_writer.get(ow, 0) + (b - a)
                loc = (lane.end - st.cursor) - rem_ops
                if rem_ops:
                    self.replica_metrics.note_merged(
                        cls, rem_ops, rem_units
                    )
                    self.merged_ops += rem_ops
                    self.merged_unit_ops += rem_units
                    if by_writer:
                        # request-trace attribution: this replica's
                        # merged ops belong to their ORIGINATING
                        # writers (obs/reqtrace.py)
                        self.reqtrace.note_remote(st.doc_id, by_writer)
                if loc:
                    self.replica_metrics.note_local(loc)
                    self.local_ops += loc
        super()._advance(plan)

    def resync_delivery(self) -> None:
        """Re-derive every replica's delivery point from the bus (used
        after crash recovery replays journaled broadcasts): the
        assembled prefix must cover the restored cursor, and the
        schedulable window resumes from it."""
        for rid, st in self.streams.items():
            g, _w = self.table.group_of(rid)
            if st.cursor > 0 and g.blocks:
                turn = g.blocks[0][1] - g.blocks[0][0]
                need = min(-(-st.cursor // turn), g.n_blocks)
                # the WAL ordering guarantees surviving lane records
                # are covered by surviving bcast records; covering the
                # cursor from the split directly is the torn-tail
                # fallback (the split is deterministic workload data).
                # A block forced below ``published`` here was published
                # pre-crash, so it must reach EVERY replica — marking
                # only the cursor's writer would strand its peers below
                # the head forever (nothing ever re-publishes a block
                # below ``published``) and livelock the resumed drain.
                for seq in range(need):
                    self.bus.force_delivered(g.logical_id, seq)
        self.bus.settle_prefixes()
        for rid, st in self.streams.items():
            st.delivered = self.bus.delivered_ops(rid)

    # ---- reporting ----

    def replication_block(self) -> dict:
        """The artifact's ``replication`` block: topology, merge load,
        fan-out, divergence and convergence-window numbers."""
        conv = self.bus.convergence_rounds()
        return {
            "version": 1,
            "writers": self.table.groups[0].writers if len(self.table)
            else 0,
            "groups": len(self.table),
            "turn_ops": self.turn_ops,
            "remote_lag": self.bus.remote_lag,
            "pub_ops": self.bus.pub_ops,
            "merged_ops": self.merged_ops,
            "merged_unit_ops": self.merged_unit_ops,
            "local_ops": self.local_ops,
            "broadcast_blocks": self.bus.blocks_published,
            "broadcast_deliveries": self.bus.blocks_delivered_remote,
            "broadcast_bytes": self.bus.bytes_broadcast,
            "divergence_depth_max": self.bus.divergence_max,
            "partitions_healed": self.bus.partitions_healed,
            "reordered_rounds": self.bus.reordered_rounds,
            "convergence_rounds_max": max(conv) if conv else 0,
            "convergence_rounds_mean": (
                sum(conv) / len(conv) if conv else 0.0
            ),
            "history_groups": sorted(self.bus.histories),
        }


def recover_replicated_fleet(
    pool, streams, table: GroupTable, journal_dir: str, *,
    journal=None, **sched_kw,
):
    """Crash recovery for a replicated fleet: restore pool/cursor state
    from the newest intact snapshot + WAL tail (``journal.recover_fleet``
    — replica rows ARE pool rows, so the plain recovery applies
    verbatim), rebuild the broadcast bus from the journaled ``bcast``
    records, and return a fresh :class:`ReplicatedScheduler` whose
    resumed drain replays the redo tail through the normal macro path
    to a CONVERGENT state.  Returns ``(scheduler, recovery_report,
    blocks_replayed)``."""
    from ..journal import read_journal, recover_fleet
    from .broadcast import replay_journal_broadcasts

    report = recover_fleet(pool, streams, journal_dir)
    records, _ = read_journal(journal_dir)
    sched = ReplicatedScheduler(
        pool, streams, table, journal=journal,
        start_round=report.resume_round, **sched_kw,
    )
    replayed = replay_journal_broadcasts(sched.bus, records)
    sched.resync_delivery()
    return sched, report, replayed
