"""The broadcast bus: op fan-out + sequence-keyed reassembly per group.

Every writer group replicates through this host-side bus.  Each tick
(one scheduler macro-round):

1. **publish** — the next turn blocks of the group's arbitration order
   (ascending block sequence) are published, paced at ``pub_ops``
   coalesced ops per group per tick so a group's producers feed the
   fleet at roughly the rate one scheduled replica can consume
   (``K * batch`` ops per macro-round).  A published block is journaled
   BEFORE any replica may consume it (``bcast`` records — the WAL's
   CRC-valid-prefix property then guarantees a surviving lane record
   implies its broadcast records survived too, which is what lets
   ``recover_fleet`` + :func:`replay_journal_broadcasts` resume to a
   convergent state);
2. **deliver** — the authoring writer's own replica receives its block
   immediately (read-your-writes); remote replicas receive it
   ``remote_lag`` ticks later, modeling propagation.  Delivery inserts
   the block into the replica's **sequence-keyed reassembly buffer**;
   the replica's *assembled prefix* (the ops the scheduler may stage)
   advances only over contiguous sequences.  Delivery order therefore
   COMMUTES: permuting a round's remote batches (the ``merge_reorder``
   chaos fault) cannot change any replica's assembled stream — the same
   transport/integration split diamond-types makes, and the reason the
   downstream merge stays verify-green under reordering;
3. **faults** — a partitioned replica (``replica_partition``) has its
   remote deliveries buffered in a per-replica backlog; at heal the
   backlog flushes in sequence order and the replica's divergence
   window (published head minus assembled prefix, in blocks) collapses
   back to the steady lag.

The bus also records the **per-replica delivery histories** for a
sampled set of groups — the raw material the RA-linearizability checker
(serve/replicate/checker.py) validates after drain — and accounts
broadcast fan-out (packed op-lane bytes delivered to remote replicas)
through ``obs/shard.py ReplicaMetrics``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ...lint.race_sanitizer import published
from .group import GroupTable, ReplicaGroup


@dataclass
class _GroupState:  # graftlint: thread=hot
    """Per-group bus state; index ``w`` = writer ``w``'s replica."""

    group: ReplicaGroup
    published: int = 0  # blocks published (a prefix of the sequence)
    last_publish_round: int = -1
    converged_round: int = -1  # every replica fully assembled
    delivered: list[list[bool]] = field(default_factory=list)
    prefix: list[int] = field(default_factory=list)  # contiguous blocks
    pending: list[tuple[int, int, int]] = field(default_factory=list)
    # pending: (ready_round, seq, dst_writer) remote deliveries in flight
    backlog: list[list[int]] = field(default_factory=list)  # per replica

    def __post_init__(self):
        W = self.group.writers
        n = self.group.n_blocks
        self.delivered = [[False] * n for _ in range(W)]
        self.prefix = [0] * W
        self.backlog = [[] for _ in range(W)]

    def advance_prefix(self, w: int) -> None:
        d = self.delivered[w]
        p = self.prefix[w]
        n = len(d)
        while p < n and d[p]:
            p += 1
        self.prefix[w] = p


class BroadcastBus:  # graftlint: thread=hot
    """Publish/deliver engine over a :class:`GroupTable` (see module
    docstring).  Host-only: no device arrays anywhere — the bus never
    syncs, so it lives inside the scheduler's sanitized hot scope
    without a fence.

    Thread confinement (G014-G016 audit, ISSUE 10): the bus is owned by
    the hot thread — the tick runs inside the macro-round, interleaved
    with staging, and every ``_GroupState`` field (delivery bitmaps,
    assembled prefixes, backlogs) is hot-confined.  The G002/G013
    hot-path walks cover the tick through ``ReplicatedScheduler``'s
    ``_plan``/``_deliver`` overrides (subclass-dispatch resolution,
    this PR); a future off-thread bus must hand batches over through a
    declared publish point."""

    def __init__(
        self,
        table: GroupTable,
        *,
        pub_ops: int,
        op_nbytes: int,
        remote_lag: int = 1,
        journal=None,
        metrics=None,
        history_groups: set[int] | None = None,
    ):
        self.table = table
        self.pub_ops = max(1, pub_ops)
        self.op_nbytes = op_nbytes
        self.remote_lag = max(0, remote_lag)
        self.journal = journal
        self.metrics = metrics  # obs/shard.py ReplicaMetrics (or None)
        self._gs = {g.logical_id: _GroupState(g) for g in table}
        # RA-checker material, recorded only for the sampled groups:
        # per replica the (round, seq) delivery order, per group the
        # (round, seq) publish order.
        self.history_groups = set(history_groups or ())
        self.histories: dict[int, list[list[tuple[int, int]]]] = {}
        self.publish_log: dict[int, list[tuple[int, int]]] = {}
        for g in table:
            if g.logical_id in self.history_groups:
                self.histories[g.logical_id] = [
                    [] for _ in range(g.writers)
                ]
                self.publish_log[g.logical_id] = []
        # faults: (gid, writer) -> (heal_round, FaultEvent|None)
        self._partitions: dict[tuple[int, int], tuple[int, object]] = {}
        self._healed_waiting: list[tuple[int, int, object]] = []
        self._reorder: tuple[object, object] | None = None  # (rng, event)
        # cumulative accounting (artifact surface)
        self.blocks_published = 0
        self.blocks_delivered_remote = 0
        self.bytes_broadcast = 0
        self.divergence_max = 0
        self.partitions_healed = 0
        self.reordered_rounds = 0

    # ---- fault arming (called by the replicated scheduler) ----

    def start_partition(self, gid: int, writer: int, heal_round: int,
                        event=None) -> None:
        self._partitions[(gid, writer)] = (heal_round, event)

    def partitioned(self, gid: int, writer: int) -> bool:
        return (gid, writer) in self._partitions

    def arm_reorder(self, rng, event=None) -> None:
        """Permute the NEXT tick's remote deliveries across writers
        (per-writer sequence order preserved — authors still emit in
        order; only the interleave is adversarial)."""
        self._reorder = (rng, event)

    def live_partition_targets(self) -> list[tuple[int, int]]:
        """(gid, writer) pairs a partition could meaningfully hit: the
        group still has undelivered future (so the divergence window
        will actually grow and the heal is observable)."""
        out = []
        for gid in sorted(self._gs):
            gs = self._gs[gid]
            if gs.group.writers < 2:
                continue
            for w in range(gs.group.writers):
                if (gs.prefix[w] < gs.group.n_blocks
                        and (gid, w) not in self._partitions):
                    out.append((gid, w))
        return out

    # ---- the tick (host-only; runs inside the hot scope) ----

    def _record(self, gid: int, w: int, rnd: int, seq: int) -> None:
        h = self.histories.get(gid)
        if h is not None:
            h[w].append((rnd, seq))

    def _deliver(self, gs: _GroupState, w: int, seq: int, rnd: int,
                 remote: bool) -> None:
        gid = gs.group.logical_id
        if remote and (gid, w) in self._partitions:
            gs.backlog[w].append(seq)
            return
        if gs.delivered[w][seq]:
            return  # duplicate delivery: reassembly is idempotent
        gs.delivered[w][seq] = True
        gs.advance_prefix(w)
        self._record(gid, w, rnd, seq)
        if remote:
            lo, hi = gs.group.block_span(seq)
            nbytes = (hi - lo) * self.op_nbytes
            self.blocks_delivered_remote += 1
            self.bytes_broadcast += nbytes
            if self.metrics is not None:
                self.metrics.note_broadcast(nbytes)

    def _heal_due(self, rnd: int) -> None:
        for key in sorted(self._partitions):
            heal_round, event = self._partitions[key]
            if rnd < heal_round:
                continue
            gid, w = key
            gs = self._gs[gid]
            del self._partitions[key]
            for seq in sorted(gs.backlog[w]):
                self._deliver(gs, w, seq, rnd, remote=True)
            gs.backlog[w] = []
            self.partitions_healed += 1
            if event is not None:
                # recovered once the replica's assembled prefix is back
                # at the published head (usually immediately: the
                # backlog flush IS the catch-up)
                self._healed_waiting.append((gid, w, event))

    def _deliver_due(self, rnd: int) -> None:
        reordered = False
        for gid in sorted(self._gs):
            gs = self._gs[gid]
            due = [p for p in gs.pending if p[0] <= rnd]
            if not due:
                continue
            gs.pending = [p for p in gs.pending if p[0] > rnd]
            if self._reorder is not None:
                rng, event = self._reorder
                # permute the WRITER interleave, preserving each
                # writer's own sequence order (authors emit in order)
                by_dst_writer: dict[tuple[int, int], list] = {}
                for ready, seq, w in due:
                    by_dst_writer.setdefault(
                        (w, gs.group.owner(seq)), []
                    ).append((ready, seq, w))
                keys = sorted(by_dst_writer)
                perm = rng.permutation(len(keys))
                due = [
                    item
                    for i in perm
                    for item in sorted(by_dst_writer[keys[int(i)]],
                                       key=lambda p: p[1])
                ]
                reordered = True
                if event is not None and not event.fired:
                    event.fire(rnd, group=gid, batches=len(due))
                    event.recover(commuted=True)
            else:
                due.sort(key=lambda p: p[1])
            for _ready, seq, w in due:
                self._deliver(gs, w, seq, rnd, remote=True)
        # one round only: the permutation is a delivery-order fault,
        # not a mode
        if reordered:
            self.reordered_rounds += 1
            self._reorder = None

    @published
    def _cross_block(self, gid: int, seq: int, owner: int) -> None:  # graftlint: publish=bus
        """The block's cross-replica propagation edge, declared as a
        publish point (``publish=bus``): publishing block ``seq`` is
        the moment writer ``owner``'s ops leave its local log and fan
        out to the group's peers.  The bus is host-side and
        hot-confined today, so no object handoff happens here — the
        point exists to COUNT the edge (G017 ground truth, one entry
        per published block) and to give request traces their bus hop
        (obs/reqtrace.py); when replication moves onto its own thread
        (ROADMAP: device-collective delivery with a host control
        plane), this becomes the real queue handoff."""

    def _publish(self, gs: _GroupState, rnd: int) -> None:
        g = gs.group
        budget = self.pub_ops
        while gs.published < g.n_blocks and budget > 0:
            seq = gs.published
            lo, hi, owner = g.blocks[seq]
            budget -= hi - lo
            gs.published = seq + 1
            gs.last_publish_round = rnd
            self.blocks_published += 1
            self._cross_block(g.logical_id, seq, owner)
            if g.logical_id in self.publish_log:
                self.publish_log[g.logical_id].append((rnd, seq))
            if self.journal is not None:
                self.journal.event(
                    "bcast", r=rnd, g=g.logical_id, w=owner, s=seq,
                    lo=lo, hi=hi,
                )
            # read-your-writes: the author's replica sees its own block
            # the moment it is published, partition or not (a partition
            # cuts the NETWORK, not the local log)
            self._deliver(gs, owner, seq, rnd, remote=False)
            for w in range(g.writers):
                if w == owner:
                    continue
                if self.remote_lag == 0:
                    self._deliver(gs, w, seq, rnd, remote=True)
                else:
                    gs.pending.append((rnd + self.remote_lag, seq, w))

    def tick(self, rnd: int) -> None:
        """One bus round: heal due partitions, deliver due remote
        blocks, publish the next paced blocks."""
        self._heal_due(rnd)
        self._deliver_due(rnd)
        for gid in sorted(self._gs):
            gs = self._gs[gid]
            if gs.published < gs.group.n_blocks:
                self._publish(gs, rnd)
            if (gs.converged_round < 0 and gs.group.n_blocks
                    and all(p == gs.group.n_blocks for p in gs.prefix)):
                gs.converged_round = rnd
        # partition events recover once the healed replica caught up
        still = []
        for gid, w, event in self._healed_waiting:
            gs = self._gs[gid]
            if gs.prefix[w] >= gs.published:
                event.recover(healed_round=rnd)
            else:
                still.append((gid, w, event))
        self._healed_waiting = still
        d = self.divergence_depth()
        if d > self.divergence_max:
            self.divergence_max = d
        if self.metrics is not None:
            self.metrics.note_divergence(d)

    # ---- recovery (force-marking outside the live tick) ----

    def force_delivered(self, gid: int, seq: int,
                        writer: int | None = None) -> None:
        """Mark block ``seq`` published and delivered (to ``writer``,
        or to every replica when None) WITHOUT the live delivery path's
        lag/partition/fan-out accounting — the recovery primitive
        shared by :func:`replay_journal_broadcasts` and the
        scheduler's torn-tail ``resync_delivery`` fallback.  Records
        the delivery into any sampled history at round ``-1`` (the
        pre-crash marker), so the RA checker still sees a complete
        arbitration prefix.  Idempotent; callers advance prefixes via
        :meth:`settle_prefixes` once a batch of marks is done."""
        gs = self._gs[gid]
        gs.published = max(gs.published, seq + 1)
        targets = range(gs.group.writers) if writer is None else (writer,)
        for w in targets:
            if not gs.delivered[w][seq]:
                gs.delivered[w][seq] = True
                self._record(gid, w, -1, seq)

    def settle_prefixes(self) -> None:
        """Re-derive every replica's assembled prefix after a batch of
        :meth:`force_delivered` marks."""
        for gs in self._gs.values():
            for w in range(gs.group.writers):
                gs.advance_prefix(w)

    # ---- queries (scheduler-facing) ----

    def delivered_ops(self, replica_id: int) -> int:
        """The replica's assembled prefix in coalesced ops — the
        delivery point the scheduler may stage up to."""
        g, w = self.table.group_of(replica_id)
        gs = self._gs[g.logical_id]
        return g.prefix_ops(gs.prefix[w])

    def divergence_depth(self) -> int:
        """Deepest replica lag right now, in turn blocks (published
        head minus assembled prefix, maxed over every replica)."""
        depth = 0
        for gs in self._gs.values():
            for p in gs.prefix:
                lag = gs.published - p
                if lag > depth:
                    depth = lag
        return depth

    def pending_work(self) -> bool:
        """True while a future tick can still move ops toward a replica
        (unpublished blocks, in-flight deliveries, partition backlogs,
        or an assembled prefix behind the published head)."""
        for gs in self._gs.values():
            if gs.published < gs.group.n_blocks or gs.pending:
                return True
            if any(gs.backlog):
                return True
            if any(p < gs.published for p in gs.prefix):
                return True
        return False

    def convergence_rounds(self) -> list[int]:
        """Per converged group: rounds from its last publish to full
        assembly on every replica (the bus-level convergence window)."""
        return [
            gs.converged_round - gs.last_publish_round
            for gs in self._gs.values()
            if gs.converged_round >= 0 and gs.last_publish_round >= 0
        ]

    def group_state(self, gid: int) -> _GroupState:
        return self._gs[gid]


def replay_journal_broadcasts(bus: BroadcastBus, records: list[dict]
                              ) -> int:
    """Rebuild bus delivery state from journaled ``bcast`` records
    (crash recovery): every journaled block is re-published and
    delivered to EVERY replica of its group — re-delivery is safe
    because the scheduler's cursor is the idempotence high-water mark
    (``DocStream.clamp_redelivery``), and the WAL's valid-prefix
    property guarantees any lane record that survived is covered by
    surviving broadcast records, so every restored cursor is within the
    re-assembled prefix.  Replayed deliveries are recorded into the
    sampled histories at round ``-1`` (the pre-crash marker) so the
    RA-linearizability checker still sees a complete, gap-free
    arbitration prefix on a recovered fleet instead of reporting
    phantom A4/A5 violations.  Returns the number of blocks replayed."""
    n = 0
    for rec in records:
        if rec.get("t") != "bcast":
            continue
        gid = int(rec["g"])
        gs = bus._gs.get(gid)
        if gs is None:
            continue
        seq = int(rec["s"])
        if seq >= gs.group.n_blocks:
            continue
        bus.force_delivered(gid, seq)
        n += 1
    bus.settle_prefixes()
    return n
