"""Open-loop load family — arrival plans, the wire client, the hot
pump, and the drive loop that replaces ``FleetScheduler.run`` for
``serve/open/<mix>/<fleet>``.

**Open loop** means arrivals do not wait for the system: each session's
ops arrive on a seeded Poisson (or burst) process at a configured
offered load (total ops per macro-round across the fleet), whether or
not the scheduler is keeping up.  Closed-loop replay measures "how
fast can the engine drain"; open loop measures "what latency does the
engine hold at THIS offered load" — which is why the knee curve
(p99 vs utilization) exists and why bench_compare gates open-loop p99
only at a fixed offered load.

The moving parts and their threads:

- :func:`build_open_plan` (driver) — turns the fleet's sessions into
  per-session frame schedules: ``(round, start, count)`` triples drawn
  from the seeded arrival process.  Immutable once built.
- :class:`OpenLoadClient` (``thread=load`` shards) — real TCP clients
  speaking the CRC frame protocol against the live front, one
  connection per session, synchronous ack per frame (in-session order
  by construction), reconnect-and-resume on churn.
- :class:`IngestPump` (hot thread) — drains the front's publish queue,
  runs per-tenant admission, and feeds admitted batches into the
  scheduler's bounded per-doc queues via ``_push_delivery`` (the same
  bounded-admission rule every other producer uses).  Frames carry
  their planned arrival round; the pump releases them no earlier —
  the wire is transport, the plan is the arrival process.
- :func:`drive_open_loop` (hot thread) — the macro-round loop: pump,
  ``run_round``, and an explicit clock tick for rounds where the
  queues are empty but producers still owe ops (the base scheduler's
  idle-jump only understands the static arrival schedule).
"""

from __future__ import annotations

import json
import math
import queue
import socket
import threading
import time

import numpy as np

from ...obs.trace import span
from .admission import DEFAULT_TENANT
from .front import encode_frame

__all__ = [
    "parse_open_spec",
    "OpenLoadPlan",
    "build_open_plan",
    "OpenLoadClient",
    "RetryBudgetExceeded",
    "IngestPump",
    "drive_open_loop",
]

#: target ops per frame: sessions whose per-round rate is tiny batch
#: several rounds into one frame (the wire stays cheap; the pump still
#: releases at the planned round).
TARGET_FRAME_OPS = 8

#: rounds a tenant flood inflates admission pressure for.
FLOOD_SPAN = 4

#: consecutive dead clock ticks (client done, nothing held, nothing
#: draining) before the drive loop declares the drain stuck.
STUCK_TICKS = 64


def parse_open_spec(spec: str) -> tuple[float, str]:
    """``RATE`` or ``RATE:poisson`` / ``RATE:burst`` → (rate, process).

    ``RATE`` is total offered ops per macro-round across the fleet.
    """
    s = str(spec).strip()
    rate_s, _, proc = s.partition(":")
    proc = proc.strip() or "poisson"
    if proc not in ("poisson", "burst"):
        raise ValueError(
            f"--serve-open: unknown arrival process {proc!r} "
            "(expected poisson or burst)"
        )
    try:
        rate = float(rate_s)
    except ValueError:
        raise ValueError(
            f"--serve-open: bad rate {rate_s!r} (want ops/round)"
        ) from None
    if rate <= 0 or not math.isfinite(rate):
        raise ValueError(f"--serve-open: rate must be positive, got {rate}")
    return rate, proc


class _SessionLoad:
    """One session's immutable send schedule."""

    __slots__ = ("session", "doc", "tenant", "frames")

    def __init__(self, session: str, doc: int, tenant: str,
                 frames: list[tuple[int, int, int]]):
        self.session = session
        self.doc = doc
        self.tenant = tenant
        self.frames = frames  # [(round, start, count)] — start-sorted


class OpenLoadPlan:
    """The whole fleet's arrival schedule (immutable after build)."""

    def __init__(self, sessions: list[_SessionLoad], *, rate: float,
                 process: str, seed: int, total_ops: int, horizon: int):
        self.sessions = sessions
        self.rate = rate
        self.process = process
        self.seed = seed
        self.total_ops = total_ops
        self.horizon = horizon
        self.tenant_of = {s.doc: s.tenant for s in sessions}
        self.total_frames = sum(len(s.frames) for s in sessions)

    def to_dict(self) -> dict:
        return {
            "rate": self.rate,
            "process": self.process,
            "seed": self.seed,
            "sessions": len(self.sessions),
            "total_ops": self.total_ops,
            "total_frames": self.total_frames,
            "horizon": self.horizon,
        }


def build_open_plan(streams, *, rate: float, process: str = "poisson",
                    seed: int = 0,
                    tenant_names=(DEFAULT_TENANT,)) -> OpenLoadPlan:
    """Draw every session's frame schedule from the seeded arrival
    process.

    The fleet's offered load ``rate`` (ops/round) is split across
    sessions proportionally to their stream lengths; each session's
    ops then arrive Poisson (per-quantum counts) or in bursts
    (geometric gaps, Poisson burst sizes) starting at its existing
    arrival round.  Tenants are assigned round-robin over the sorted
    tenant names (deterministic given the doc order).
    """
    rng = np.random.default_rng(seed)
    tenants = sorted(tenant_names) or [DEFAULT_TENANT]
    docs = sorted(streams)
    total = sum(max(0, streams[d].n_total) for d in docs)
    if total <= 0:
        raise ValueError("open plan: fleet has no ops to offer")
    sessions: list[_SessionLoad] = []
    horizon = 0
    duration = max(1, int(math.ceil(total / rate)))
    for i, doc in enumerate(docs):
        st = streams[doc]
        n = st.n_total
        if n <= 0:
            continue
        lam = rate * n / total
        arrival = int(st.arrival)
        # flush anything still unsent past this point: a straggler tail
        # must not stretch the drain unboundedly (counted in the frame
        # schedule, not silently dropped)
        flush_at = arrival + max(64, 8 * duration)
        tenant = tenants[i % len(tenants)]
        frames: list[tuple[int, int, int]] = []
        cum = 0
        if process == "burst":
            burst = max(4.0, lam * 8.0)
            p = min(1.0, lam / burst)
            r = arrival
            while cum < n:
                r += int(rng.geometric(p))
                if r >= flush_at:
                    frames.append((flush_at, cum, n - cum))
                    cum = n
                    break
                k = 1 + int(rng.poisson(burst - 1.0))
                k = min(k, n - cum)
                frames.append((r, cum, k))
                cum += k
        else:
            q = 1 if lam >= TARGET_FRAME_OPS else min(
                16, int(math.ceil(TARGET_FRAME_OPS / lam)))
            r = arrival
            while cum < n:
                if r >= flush_at:
                    frames.append((flush_at, cum, n - cum))
                    cum = n
                    break
                k = int(rng.poisson(lam * q))
                k = min(k, n - cum)
                if k > 0:
                    frames.append((r, cum, k))
                    cum += k
                r += q
        if frames:
            horizon = max(horizon, frames[-1][0])
        sessions.append(_SessionLoad(f"s{doc}", doc, tenant, frames))
    return OpenLoadPlan(sessions, rate=rate, process=process, seed=seed,
                        total_ops=total, horizon=horizon)


class RetryBudgetExceeded(RuntimeError):
    """A session burned its whole retry budget without progress — the
    front is unreachable (dead listener) or permanently refusing.  The
    typed error carries enough to act on: silent ``errors`` counters
    made a dead listener look like load-shedding."""

    def __init__(self, session: str, doc: int, attempts: int,
                 elapsed_s: float, last_error: str):
        super().__init__(
            f"session {session} (doc {doc}): retry budget exhausted "
            f"after {attempts} attempts over {elapsed_s:.2f}s "
            f"(last error: {last_error})"
        )
        self.session = session
        self.doc = doc
        self.attempts = attempts
        self.elapsed_s = elapsed_s
        self.last_error = last_error


class _Backoff:
    """Capped exponential backoff with seeded jitter and a TOTAL retry
    budget.  ``sleep()`` returns False once the budget is spent —
    progress (an acked frame) resets the exponent, never the budget,
    so a flapping front still terminates."""

    def __init__(self, rng, *, base: float, cap: float, budget: int):
        self.rng = rng
        self.base = float(base)
        self.cap = float(cap)
        self.budget = int(budget)
        self.attempts = 0  # total, never reset
        self._streak = 0  # consecutive failures, reset on progress

    def sleep(self) -> bool:
        self.attempts += 1
        if self.attempts > self.budget:
            return False
        delay = min(self.cap, self.base * (2.0 ** self._streak))
        self._streak += 1
        # full jitter (seeded): uniform over (0.5, 1.0] * delay keeps
        # the expected wait near delay while decorrelating shards
        time.sleep(delay * (0.5 + 0.5 * float(self.rng.random())))
        return True

    def progress(self) -> None:
        self._streak = 0


class OpenLoadClient:
    """Sharded wire clients replaying an :class:`OpenLoadPlan` against
    a live front.

    Each shard thread (``thread=load``) walks its sessions
    sequentially: connect, ``hello``, synchronous ``ops`` frames (ack
    per frame — in-session order by construction), ``bye``.  A
    ``retry`` reply (pump backpressure) re-sends the same frame; a
    ``churn`` reply or socket error reconnects with ``resume`` —
    delivery is idempotent downstream, so redelivery is safe.  Shard
    results cross back through a plain results queue read only after
    the shards finish.

    Every retry path — connect refusals, socket drops, ``retry``
    backpressure — shares one per-session :class:`_Backoff`: capped
    exponential delays with seeded jitter and a total budget of
    ``retry_budget`` attempts.  A session that exhausts the budget
    raises :class:`RetryBudgetExceeded`; ``join()`` re-raises the
    first such failure on the driver thread.
    """

    RETRY_BASE_S = 0.005
    RETRY_CAP_S = 0.25
    RETRY_BUDGET = 128

    def __init__(self, port: int, plan: OpenLoadPlan, *, shards: int = 2,
                 connect_timeout: float = 10.0, seed: int | None = None,
                 retry_base: float | None = None,
                 retry_cap: float | None = None,
                 retry_budget: int | None = None):
        self.port = int(port)
        self.plan = plan
        self.shards = max(1, min(int(shards), len(plan.sessions) or 1))
        self.connect_timeout = float(connect_timeout)
        self.seed = int(plan.seed if seed is None else seed)
        self.retry_base = float(self.RETRY_BASE_S if retry_base is None
                                else retry_base)
        self.retry_cap = float(self.RETRY_CAP_S if retry_cap is None
                               else retry_cap)
        self.retry_budget = int(self.RETRY_BUDGET if retry_budget is None
                                else retry_budget)
        self._threads: list[threading.Thread] = []
        self._done_q: queue.Queue = queue.Queue()
        self._failures: queue.Queue = queue.Queue()
        # aggregated by join() after every shard reported
        self.sent_frames = 0
        self.retries = 0
        self.reconnects = 0
        self.errors = 0

    # ---- driver-side lifecycle ----

    def start(self) -> None:
        for i in range(self.shards):
            t = threading.Thread(
                target=self._run_shard, args=(i,),
                name=f"serve-ingest-load-{i}", daemon=True,
            )
            self._threads.append(t)
            t.start()

    @property
    def finished(self) -> bool:
        """True once every shard reported (hot-safe: qsize only)."""
        return self._done_q.qsize() >= self.shards

    def join(self, timeout: float = 60.0) -> None:
        for t in self._threads:
            t.join(timeout=timeout)
        while True:
            try:
                sent, retries, reconnects, errors = self._done_q.get_nowait()
            except queue.Empty:
                break
            self.sent_frames += sent
            self.retries += retries
            self.reconnects += reconnects
            self.errors += errors
        try:
            raise self._failures.get_nowait()
        except queue.Empty:
            pass

    def to_dict(self) -> dict:
        return {
            "shards": self.shards,
            "sent_frames": self.sent_frames,
            "retries": self.retries,
            "reconnects": self.reconnects,
            "errors": self.errors,
            "retry_budget": self.retry_budget,
        }

    # ---- the load threads ----

    def _run_shard(self, shard: int) -> None:  # graftlint: thread=load
        sent = retries = reconnects = errors = 0
        try:
            for sess in self.plan.sessions[shard::self.shards]:
                try:
                    s, r, rc, e = self._run_session(sess)
                except RetryBudgetExceeded as exc:
                    # surface the TYPED failure to join() instead of
                    # burying it in a counter; remaining sessions on
                    # this shard are abandoned (the front is dead)
                    self._failures.put(exc)
                    errors += 1
                    break
                sent += s
                retries += r
                reconnects += rc
                errors += e
        finally:
            self._done_q.put((sent, retries, reconnects, errors))

    def _run_session(self, sess: _SessionLoad
                     ) -> tuple[int, int, int, int]:  # graftlint: thread=load
        sent = retries = reconnects = 0
        seq = 0
        idx = 0
        resume = False
        t0 = time.perf_counter()
        # one backoff per session, seeded from (client seed, doc): the
        # jitter sequence is deterministic given the plan, and distinct
        # sessions never sleep in lockstep
        bo = _Backoff(
            np.random.default_rng((self.seed << 20) ^ (sess.doc + 1)),
            base=self.retry_base, cap=self.retry_cap,
            budget=self.retry_budget,
        )

        def _spend(last: str) -> None:
            if not bo.sleep():
                raise RetryBudgetExceeded(
                    sess.session, sess.doc, bo.attempts - 1,
                    time.perf_counter() - t0, last,
                )

        while idx < len(sess.frames) or not resume:
            try:
                sk = socket.create_connection(
                    ("127.0.0.1", self.port), timeout=self.connect_timeout)
            except OSError as e:
                _spend(f"connect: {e}")
                continue
            try:
                f = sk.makefile("rwb")
                resp = self._xchg(f, {
                    "t": "hello", "session": sess.session,
                    "doc": sess.doc, "tenant": sess.tenant,
                    "resume": resume,
                })
                if resp.get("t") == "churn":
                    # churn fired between accept and hello: the handler
                    # saw a stale generation — reconnect like any drop
                    raise _Churned()
                if resp.get("t") != "ack":
                    return sent, retries, reconnects, 1
                while idx < len(sess.frames):
                    rnd, start, count = sess.frames[idx]
                    resp = self._xchg(f, {
                        "t": "ops", "seq": seq, "start": start,
                        "count": count, "round": rnd,
                    })
                    t = resp.get("t")
                    if t == "ack":
                        seq += 1
                        idx += 1
                        sent += 1
                        bo.progress()
                    elif t == "retry":
                        retries += 1
                        _spend("pump backpressure (retry)")
                    elif t == "churn":
                        raise _Churned()
                    else:
                        return sent, retries, reconnects, 1
                self._xchg(f, {"t": "bye", "session": sess.session})
                return sent, retries, reconnects, 0
            except _Churned:
                reconnects += 1
                resume = True
            except (OSError, ValueError) as e:
                reconnects += 1
                resume = True
                _spend(f"{type(e).__name__}: {e}")
            finally:
                try:
                    sk.close()
                except OSError:
                    pass
        return sent, retries, reconnects, 0

    @staticmethod
    def _xchg(f, obj: dict) -> dict:
        f.write(encode_frame(obj))
        f.flush()
        line = f.readline()
        if not line:
            raise OSError("connection closed")
        out = json.loads(line)
        if not isinstance(out, dict):
            raise ValueError("bad reply")
        return out


class _Churned(Exception):
    """Server dropped us (conn_churn): reconnect and resume."""


class IngestPump:
    """Hot-side glue: front → admission → bounded per-doc queues.

    Owns all cross-layer accounting (the ingest block of /status.json
    and the artifact).  Everything here runs on the hot thread; the
    only upstream contact is ``front.drain()`` (non-blocking) and the
    only downstream contact is the scheduler's own bounded-admission
    rule ``_push_delivery``."""

    def __init__(self, sched, front, admission, *, tenant_of,
                 faults=None):
        self.sched = sched
        self.front = front
        self.admission = admission
        self.tenant_of = dict(tenant_of)
        self.faults = faults
        self._holding: list[list] = []  # [payload, due_round, defers]
        self._klass: dict[int, str] = {}
        # counters (hot-owned)
        self.late_frames = 0
        self.admitted_frames = 0
        self.dup_frames = 0
        self.shed_docs = 0
        self.drained_frames = 0
        # chaos bookkeeping
        self._churn_ev = None
        self._churn_mark = 0
        self._flood_ev = None
        self._flood_tenant: str | None = None
        self._flood_factor = 1
        self._flood_until = -1
        self._flood_deferred = 0
        self._flood_shed = 0

    @property
    def idle(self) -> bool:
        return not self._holding and self.front.idle

    def _slo_class(self, doc: int) -> str:
        k = self._klass.get(doc)
        if k is None:
            rec = self.sched.pool.docs[doc]
            cls = self.sched.pool.class_for(max(rec.length, 1))
            slo = self.admission.slo
            k = slo.classify(cls) if slo is not None else "default"
            self._klass[doc] = k
        return k

    def step(self, rnd: int) -> bool:  # graftlint: thread=hot
        """One macro-round of intake: chaos hooks, bucket refill,
        drain the front, admit everything due.  Returns True while the
        pump still holds (or the front still buffers) work."""
        self._fault_hooks(rnd)
        self.front.now = rnd  # publish the clock (immutable int swap)
        self.admission.refill()
        for payload in self.front.drain():
            self.drained_frames += 1
            kind = payload.get("kind")
            if kind == "ops":
                due = int(payload.get("round", 0))
                if due < rnd:
                    self.late_frames += 1
                self._holding.append([payload, max(due, rnd), 0])
            elif kind == "hello":
                ev = self._churn_ev
                if (payload.get("resume") and ev is not None and ev.fired
                        and not ev.recovered
                        and self.front.churn_drops > 0):
                    ev.recover(resumed=payload.get("session"), round=rnd)
        self._admit(rnd)
        return bool(self._holding) or not self.front.idle

    def _fault_hooks(self, rnd: int) -> None:
        f = self.faults
        if f is None:
            return
        if self._churn_ev is None:
            ev = f.conn_churn_event(rnd)
            if ev is not None:
                self.front.churn()
                ev.fire(rnd, gen=self.front.churn_gen)
                self._churn_ev = ev
                self._churn_mark = self.front.ops_delivered
                self.sched.stats.faults_injected += 1
                self.sched._note_fault()
        else:
            ev = self._churn_ev
            # fallback recovery: traffic flowing again after the drop
            # (a resumed hello is the usual evidence; ops resuming is
            # just as conclusive when the hello raced the drain)
            if (ev.fired and not ev.recovered
                    and self.front.churn_drops > 0
                    and self.front.ops_delivered > self._churn_mark):
                ev.recover(via="traffic_resumed", round=rnd)
        flood = self._flood_ev
        if flood is not None and not flood.recovered and rnd > self._flood_until:
            flood.recover(round=rnd, deferred_ops=self._flood_deferred,
                          shed_ops=self._flood_shed)
        if flood is None or flood.recovered:
            ev = f.tenant_flood_event(rnd)
            if ev is not None:
                tenant = sorted(self.admission.policies)[0]
                factor = ev.param or 8
                self._flood_ev = ev
                self._flood_tenant = tenant
                self._flood_factor = factor
                self._flood_until = rnd + FLOOD_SPAN
                self._flood_deferred = 0
                self._flood_shed = 0
                ev.fire(rnd, tenant=tenant, factor=factor,
                        until=self._flood_until)
                self.sched.stats.faults_injected += 1
                self.sched._note_fault()

    def _flooding(self, tenant: str, rnd: int) -> bool:
        return (self._flood_ev is not None and self._flood_ev.fired
                and tenant == self._flood_tenant
                and rnd <= self._flood_until)

    def _admit(self, rnd: int) -> None:  # graftlint: thread=hot
        sched = self.sched
        adm = self.admission
        # per-tenant in-queue ops, computed once per round
        pending: dict[str, int] = {}
        for doc, st in sched.streams.items():
            if st.delivered is None:
                continue
            t = self.tenant_of.get(doc, DEFAULT_TENANT)
            pending[t] = pending.get(t, 0) + max(0, st.n_sched - st.cursor)
        keep: list[list] = []
        blocked: set[int] = set()  # docs whose earlier frame stalled
        for item in self._holding:
            payload, due, defers = item
            doc = payload["doc"]
            if due > rnd or doc in blocked:
                keep.append(item)
                continue
            st = sched.streams[doc]
            start = int(payload["start"])
            count = int(payload["count"])
            want = start + count
            if st.lossy:
                want = min(want, st.n_total)
            delivered = st.delivered or 0
            if want <= delivered:
                # redelivery (resume) or post-shed tail: idempotent drop
                sched.stats.dup_ops_dropped += st.clamp_redelivery(
                    start, min(want, st.cursor))
                self.dup_frames += 1
                continue
            tenant = payload.get("tenant", DEFAULT_TENANT)
            eff = count * self._flood_factor if self._flooding(tenant, rnd) \
                else count
            verb, _reason = adm.decide(
                tenant, eff, self._slo_class(doc),
                pending.get(tenant, 0), defers)
            if verb == "defer":
                item[1] = rnd + 1
                item[2] = defers + 1
                blocked.add(doc)
                keep.append(item)
                if self._flooding(tenant, rnd):
                    self._flood_deferred += count
                continue
            if verb == "shed":
                keep_at = max(st.cursor, delivered)
                prev = st.n_total
                st.limit = keep_at if st.limit is None \
                    else min(st.limit, keep_at)
                st.lossy = True
                shed = prev - st.n_total
                sched.stats.shed_ops += shed
                adm.journal_shed(doc, keep_at, shed, tenant, rnd)
                self.shed_docs += 1
                blocked.add(doc)
                if self._flooding(tenant, rnd):
                    self._flood_shed += shed
                continue
            # admit: the scheduler's bounded-queue rule owns the clamp
            before = st.delivered or 0
            excess = sched._push_delivery(st, want)
            pending[tenant] = pending.get(tenant, 0) + max(
                0, (st.delivered or 0) - before)
            if excess:
                # hold the refused tail; the accepted prefix is already
                # in (delivery is an offset high-water mark)
                item[0] = {**payload, "start": int(st.delivered),
                           "count": int(want - st.delivered)}
                item[1] = rnd + 1
                blocked.add(doc)
                keep.append(item)
            else:
                self.admitted_frames += 1
        self._holding = keep

    def status_fields(self) -> dict:  # graftlint: thread=hot
        """The ``ingest`` sub-block for /status.json: front gauges,
        admission totals, pump counters, chaos state."""
        out = self.front.status_fields()
        out["admission"] = self.admission.status_fields()
        out["holding_frames"] = len(self._holding)
        out["late_frames"] = self.late_frames
        out["admitted_frames"] = self.admitted_frames
        out["dup_frames"] = self.dup_frames
        out["shed_docs"] = self.shed_docs
        return out

    def to_dict(self) -> dict:
        out = self.status_fields()
        out["drained_frames"] = self.drained_frames
        return out


def drive_open_loop(sched, pump, client, *, max_rounds=None,
                    wire_sleep: float = 0.0005,
                    log=None):  # graftlint: thread=hot
    """The open-loop drain: pump → ``run_round`` → explicit clock tick
    when the queues are empty but producers still owe ops (the base
    idle-jump only understands the static arrival schedule).  Epilogue
    mirrors ``FleetScheduler.run`` — final device fence, pending-round
    fold, fault sweep — so the stats and artifact shapes match the
    closed-loop path exactly."""
    t0 = time.perf_counter()
    n = 0
    dead_ticks = 0
    while True:
        live = pump.step(sched.round)
        progressed = sched.run_round()
        if progressed:
            n += 1
            dead_ticks = 0
            if max_rounds is not None and n >= max_rounds:
                break
            continue
        wire_live = not client.finished
        if sched.done and not live and not wire_live:
            break
        if not live and not wire_live:
            # queues drained, nothing held, client done — yet streams
            # still owe ops: give the front's buffer a bounded chance
            # to surface stragglers, then call it stuck
            dead_ticks += 1
            if dead_ticks > STUCK_TICKS:
                missing = sorted(
                    d for d, s in sched.streams.items() if s.remaining
                )[:8]
                raise RuntimeError(
                    "open-loop drain stuck: client finished but docs "
                    f"still owe ops (first: {missing})"
                )
        else:
            dead_ticks = 0
        # the open-loop clock ticks whether or not anything scheduled
        sched.round += 1
        if wire_live and not live:
            time.sleep(wire_sleep)  # waiting on the wire, not the CPU
    tail0 = time.perf_counter()
    with span("serve.drain_fence"):
        sched.pool.block()
    if sched._pending_round is not None:
        dt, c, b = sched._pending_round
        sched._pending_round = (dt + time.perf_counter() - tail0, c, b)
    sched._flush_round()
    if sched.faults is not None and sched.done:
        with span("serve.finalize_faults"):
            sched.finalize_faults()
    sched.stats.wall_time += time.perf_counter() - t0
    sched.stats.evictions = sched.pool.evictions
    sched.stats.restores = sched.pool.restores
    sched.stats.promotions = sched.pool.promotions
    return sched.stats
