"""Per-tenant admission control for the live ingest front.

Every op batch the front delivers carries a tenant; before the pump
pushes it into a bounded per-doc queue the batch passes through one
``AdmissionController.decide`` call that returns one of three verbs:

- **admit** — tokens consumed, ops flow into the doc's bounded queue;
- **defer** — the pump holds the batch and retries next macro-round
  (token bucket empty, queue budget full, or the tenant's SLO class is
  burning error budget faster than it refills — a fast-window spike);
- **shed** — the doc's stream is tail-dropped at the current delivery
  point, exactly like the scheduler's ``queue_overflow`` shed: the
  decision is journaled as a ``t="shed"`` record (with a ``tenant``
  field the replay ignores) so ``recover_fleet`` replays it with zero
  new recovery code.  Shed fires on a SUSTAINED burn (fast AND slow
  windows > 1.0) or when a batch has been deferred ``MAX_DEFERS``
  times — defer is a promise to retry, not a place to park ops
  forever.

The burn-rate inputs come from ``obs/slo.py``: burn > 1.0 means the
class is consuming error budget faster than the window refills it.
Fast-window-only burn is a spike (defer and let it decay); fast+slow
is a sustained incident (shed — the tenant is not going to catch up).

Tenant policy grammar (``--serve-tenants``)::

    name=RATE[:BURST[:BUDGET]][,name=...]

``RATE`` is tokens (ops) refilled per macro-round; ``BURST`` is the
bucket depth (default ``4*RATE``); ``BUDGET`` caps the tenant's total
in-queue ops across its docs (default 0 = unbounded).  Example:
``gold=256:1024,free=16:32:256``.

Confinement: the controller is HOT-OWNED — ``decide``/``refill`` run
only on the hot pump; the ingest handler threads never touch it.  All
metrics are pre-registered in ``bind`` (G013), labeled per tenant.
"""

import math

__all__ = [
    "TenantSpecError",
    "TenantPolicy",
    "parse_tenant_spec",
    "AdmissionController",
    "DEFAULT_TENANT",
]

DEFAULT_TENANT = "default"


class TenantSpecError(ValueError):
    """A ``--serve-tenants`` spec that does not parse."""


class TenantPolicy:
    """One tenant's admission knobs (immutable after construction)."""

    __slots__ = ("name", "rate", "burst", "budget")

    def __init__(self, name: str, rate: float, burst: float = 0.0,
                 budget: int = 0):
        if not name:
            raise TenantSpecError("tenant name must be non-empty")
        if rate <= 0 or not math.isfinite(rate):
            raise TenantSpecError(
                f"tenant {name!r}: rate must be a positive finite "
                f"ops/round, got {rate!r}"
            )
        if burst < 0 or budget < 0:
            raise TenantSpecError(
                f"tenant {name!r}: burst/budget must be >= 0"
            )
        self.name = name
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else 4.0 * self.rate
        self.budget = int(budget)

    def to_dict(self) -> dict:
        return {"rate": self.rate, "burst": self.burst,
                "budget": self.budget}


def parse_tenant_spec(spec: str) -> dict[str, TenantPolicy]:
    """Parse ``name=RATE[:BURST[:BUDGET]],...`` into policies.

    Raises :class:`TenantSpecError` on malformed entries, duplicate
    tenants, or non-numeric fields — the runner surfaces the message
    and exits 2, mirroring ``parse_slo_spec``.
    """
    out: dict[str, TenantPolicy] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, eq, rhs = part.partition("=")
        name = name.strip()
        if not eq or not name or not rhs:
            raise TenantSpecError(
                f"bad tenant entry {part!r} (want name=RATE[:BURST[:BUDGET]])"
            )
        if name in out:
            raise TenantSpecError(f"duplicate tenant {name!r}")
        fields = rhs.split(":")
        if len(fields) > 3:
            raise TenantSpecError(
                f"tenant {name!r}: too many ':' fields in {rhs!r}"
            )
        try:
            rate = float(fields[0])
            burst = float(fields[1]) if len(fields) > 1 else 0.0
            budget = int(fields[2]) if len(fields) > 2 else 0
        except ValueError as e:
            raise TenantSpecError(
                f"tenant {name!r}: non-numeric field in {rhs!r}"
            ) from e
        out[name] = TenantPolicy(name, rate, burst, budget)
    if not out:
        raise TenantSpecError(f"empty tenant spec {spec!r}")
    return out


class AdmissionController:
    """Hot-owned admit/defer/shed policy over per-tenant token buckets.

    ``refill()`` runs once per macro-round (refills buckets, snapshots
    SLO burns); ``decide()`` runs once per delivered batch.  Decisions
    never block and never touch the network — the front's handler
    threads see only their payload's ack, the pump owns everything
    here.
    """

    #: a batch deferred this many times escalates to shed — defer is
    #: backpressure, not an unbounded parking lot (and the open-loop
    #: drive must terminate even under a sustained burn).
    MAX_DEFERS = 64

    def __init__(self, policies: dict[str, TenantPolicy], *,
                 slo=None, journal=None):
        self.policies = dict(policies)
        self.slo = slo
        self.journal = journal
        self.tokens = {t: p.burst for t, p in self.policies.items()}
        self.admitted_ops = {t: 0 for t in self.policies}
        self.deferred_ops = {t: 0 for t in self.policies}
        self.shed_ops = {t: 0 for t in self.policies}
        self.decisions: dict[str, int] = {}
        self._burns: dict[str, tuple[float, float]] = {}
        self._counters = None  # (tenant, verb) -> Counter, set by bind
        self._token_gauges = None

    # ---- driver-side wiring (off the hot call graph) ----

    def bind(self, registry) -> None:
        """Pre-register the per-tenant counters and token gauges so the
        hot path only ever touches held references (G013)."""
        counters = {}
        gauges = {}
        for t in self.policies:
            for verb in ("admitted", "deferred", "shed"):
                counters[(t, verb)] = registry.counter(
                    f'serve.ingest.{verb}_ops{{tenant="{t}"}}'
                )
            gauges[t] = registry.gauge(
                f'serve.ingest.tokens{{tenant="{t}"}}'
            )
        self._counters = counters
        self._token_gauges = gauges

    def policy_for(self, tenant: str) -> TenantPolicy:
        try:
            return self.policies[tenant]
        except KeyError:
            raise KeyError(
                f"unknown tenant {tenant!r} (declared: "
                f"{', '.join(sorted(self.policies))})"
            ) from None

    # ---- hot pump surface ----

    def refill(self) -> None:  # graftlint: thread=hot
        """Once per macro-round: refill buckets and snapshot the SLO
        class burns the round's decisions will read."""
        for t, p in self.policies.items():
            tok = min(p.burst, self.tokens[t] + p.rate)
            self.tokens[t] = tok
            if self._token_gauges is not None:
                self._token_gauges[t].set(tok)
        if self.slo is not None:
            # one status snapshot per round, not one per class
            burns = {}
            fields = self.slo.status_fields().get("classes", {})
            for name, d in fields.items():
                burns[name] = (float(d.get("burn_fast", 0.0)),
                               float(d.get("burn_slow", 0.0)))
            self._burns = burns

    def burn(self, klass: str) -> tuple[float, float]:
        """(fast, slow) burn for an SLO class name; 0.0 when unknown."""
        return self._burns.get(klass, (0.0, 0.0))

    def decide(self, tenant: str, ops: int, klass: str,
               pending: int, defers: int = 0
               ) -> tuple[str, str]:  # graftlint: thread=hot
        """One batch's verdict: ``("admit"|"defer"|"shed", reason)``.

        ``pending`` is the tenant's total in-queue ops (delivered but
        not yet drained) BEFORE this batch; ``defers`` is how many
        rounds this same batch has already been pushed back.
        """
        p = self.policy_for(tenant)
        fast, slow = self.burn(klass)
        if fast > 1.0 and slow > 1.0:
            return self._note(tenant, "shed", "burn_sustained", ops)
        if defers >= self.MAX_DEFERS:
            return self._note(tenant, "shed", "defer_limit", ops)
        if fast > 1.0:
            return self._note(tenant, "defer", "burn_spike", ops)
        if p.budget and pending + ops > p.budget:
            return self._note(tenant, "defer", "queue_budget", ops)
        if self.tokens[tenant] < ops:
            return self._note(tenant, "defer", "tokens", ops)
        self.tokens[tenant] -= ops
        return self._note(tenant, "admit", "ok", ops)

    def journal_shed(self, doc_id: int, keep: int, shed: int,
                     tenant: str, rnd: int) -> None:  # graftlint: thread=hot
        """Journal an admission shed with the overflow-shed record
        shape — ``recover_fleet`` replays ``t="shed"`` by (doc, at,
        ops) and ignores the extra ``tenant``/``why`` fields, so
        recovery parity costs zero new replay code."""
        if self.journal is not None:
            self.journal.event("shed", r=rnd, doc=doc_id, at=keep,
                               ops=shed, tenant=tenant, why="admission")

    def _note(self, tenant: str, verb: str, reason: str, ops: int
              ) -> tuple[str, str]:
        key = f"{verb}:{reason}"
        self.decisions[key] = self.decisions.get(key, 0) + 1
        bucket = {"admit": self.admitted_ops, "defer": self.deferred_ops,
                  "shed": self.shed_ops}[verb]
        bucket[tenant] = bucket.get(tenant, 0) + ops
        if self._counters is not None:
            self._counters[(tenant, {"admit": "admitted",
                                     "defer": "deferred",
                                     "shed": "shed"}[verb])].inc(ops)
        return verb, reason

    # ---- reporting ----

    def status_fields(self) -> dict:
        """The /status.json + artifact sub-block: per-tenant totals and
        the decision histogram."""
        return {
            "tenants": {
                t: {
                    "tokens": round(self.tokens[t], 3),
                    "admitted_ops": self.admitted_ops.get(t, 0),
                    "deferred_ops": self.deferred_ops.get(t, 0),
                    "shed_ops": self.shed_ops.get(t, 0),
                }
                for t in self.policies
            },
            "decisions": dict(sorted(self.decisions.items())),
        }

    def to_dict(self) -> dict:
        out = self.status_fields()
        out["policies"] = {t: p.to_dict()
                          for t, p in self.policies.items()}
        return out
