"""The thread-confined TCP ingest front.

Sibling of ``obs/status.py``'s HTTP server, with the same confinement
story inverted: /status flows hot→handler, ingest flows handler→hot.

**Wire format** — one frame per line::

    <crc32:08x> <json>\\n

where the checksum covers the JSON bytes exactly (the journal's line
convention).  Frame kinds, all JSON objects with a ``t`` field:

- ``hello`` ``{t, session, doc, tenant, resume?}`` — binds this
  connection to ONE session writing ONE doc.  ``resume`` marks a
  reconnect after a drop (connection churn): delivery is idempotent
  downstream (``delivered`` is monotonic, redelivery clamps), so a
  resumed session simply re-sends from its last acked offset.
- ``ops`` ``{t, seq, start, count}`` — "deliver the next ``count``
  ops of this session's stream starting at absolute op offset
  ``start``".  ``seq`` must be strictly increasing per connection;
  the server acks each frame (``{"t":"ack","seq":n}``) before the
  client sends the next, so in-session order is preserved into the
  scheduler's bounded per-doc queue by construction.
- ``bye`` ``{t, session}`` — clean close.

Server replies are unframed JSON lines: ``ack`` / ``retry`` (delivery
queue full, or the frame's planned round is still ahead of the server
clock — re-send the same frame; the wire itself paces the open-loop
arrival process) / ``err`` (protocol violation — connection closes) /
``churn`` (the chaos fault dropped you — reconnect and resume).

**Confinement** (G013–G017 + the runtime race sanitizer): handler
threads are ``thread=ingest`` and own nothing but their connection
state; every payload crosses to the hot pump through ONE declared
``publish=ingest`` swap point on a bounded queue, and the pump's
:meth:`IngestFront.drain` is the ``reveal`` gate.  All counters are
hot-owned — handler-side events (bad CRC, churn drops) ride the
published payloads and are tallied at drain.  The hot side signals
handlers only through :meth:`churn`'s immutable generation bump (the
``set_health`` pattern: an atomic int swap needs no publish point).
"""

from __future__ import annotations

import json
import socketserver
import queue
import threading
import zlib

from ...lint import lifecycle_sanitizer as lifecycle
from ...lint.race_sanitizer import published, reveal, share

__all__ = ["IngestFront", "encode_frame", "decode_frame", "FRAME_KINDS"]

FRAME_KINDS = ("hello", "ops", "bye")

#: delivery-queue bound: deep enough to absorb a macro-round of frames
#: from every live connection, small enough that a stalled pump turns
#: into client-visible ``retry`` backpressure instead of memory growth.
DEFAULT_CAPACITY = 1024


def encode_frame(obj: dict) -> bytes:
    """One CRC-framed wire line for ``obj`` (client side + tests)."""
    body = json.dumps(obj, separators=(",", ":"), sort_keys=True)
    raw = body.encode("utf-8")
    return f"{zlib.crc32(raw):08x} ".encode("ascii") + raw + b"\n"


def decode_frame(line: bytes) -> dict:
    """Parse + verify one wire line; raises ``ValueError`` on a short
    line, a CRC mismatch, or non-object JSON."""
    line = line.rstrip(b"\r\n")
    if len(line) < 10 or line[8:9] != b" ":
        raise ValueError("short frame")
    try:
        want = int(line[:8], 16)
    except ValueError:
        raise ValueError("bad crc field") from None
    raw = line[9:]
    got = zlib.crc32(raw)
    if got != want:
        raise ValueError(f"crc mismatch (want {want:08x} got {got:08x})")
    obj = json.loads(raw.decode("utf-8"))
    if not isinstance(obj, dict) or "t" not in obj:
        raise ValueError("frame is not an object with 't'")
    return obj


class _IngestHandler(socketserver.StreamRequestHandler):  # graftlint: thread=ingest
    """One connection = one session = one doc.  Connection-local state
    only; everything leaving this thread goes through the front's
    declared publish point."""

    def handle(self) -> None:
        front: IngestFront = self.server.owner  # type: ignore[attr-defined]
        churn_gen = front.churn_gen  # generation at accept
        session = doc = tenant = None
        last_seq = -1
        while True:
            try:
                line = self.rfile.readline(front.max_frame)
            except OSError:
                return
            if not line:
                return  # peer closed
            if front.churn_gen != churn_gen:
                # the chaos fault dropped this connection: tell the
                # client to reconnect-and-resume, surface the drop to
                # the pump (that is the fault's "fire" evidence; session
                # is None when churn raced the hello — still a drop)
                front.publish({"kind": "churn_drop",
                               "session": session, "doc": doc,
                               "tenant": tenant})
                self._reply({"t": "churn"})
                return
            try:
                frame = decode_frame(line)
            except ValueError as e:
                front.publish({"kind": "bad_frame", "why": str(e)})
                self._reply({"t": "err", "why": str(e)})
                return
            kind = frame.get("t")
            if kind == "hello":
                if session is not None:
                    self._reply({"t": "err", "why": "double hello"})
                    return
                session = frame.get("session")
                doc = frame.get("doc")
                tenant = frame.get("tenant", "default")
                if doc not in front.valid_docs:
                    self._reply({"t": "err", "why": f"unknown doc {doc!r}"})
                    return
                if tenant not in front.tenant_names:
                    self._reply(
                        {"t": "err", "why": f"unknown tenant {tenant!r}"})
                    return
                front.publish({"kind": "hello", "session": session,
                               "doc": doc, "tenant": tenant,
                               "resume": bool(frame.get("resume"))})
                self._reply({"t": "ack", "seq": -1})
            elif kind == "ops":
                if session is None:
                    self._reply({"t": "err", "why": "ops before hello"})
                    return
                seq = int(frame.get("seq", -1))
                if seq <= last_seq:
                    front.publish({"kind": "bad_frame",
                                   "why": f"seq regression {seq}"})
                    self._reply({"t": "err",
                                 "why": f"seq {seq} <= {last_seq}"})
                    return
                rnd = int(frame.get("round", 0))
                if rnd > front.now + front.pace_slack:
                    # planned arrival still in the future: the wire
                    # paces the open loop — same retry contract as a
                    # full queue, frame NOT acked, client re-sends
                    self._reply({"t": "retry", "seq": seq})
                    continue
                payload = {
                    "kind": "ops", "session": session, "doc": doc,
                    "tenant": tenant, "seq": seq,
                    "start": int(frame.get("start", 0)),
                    "count": int(frame.get("count", 0)),
                    "round": rnd,
                }
                if not front.publish(payload, timeout=front.put_timeout):
                    # bounded queue full: client-visible backpressure,
                    # frame NOT acked — the client re-sends it, so no
                    # ops are lost and order is preserved
                    self._reply({"t": "retry", "seq": seq})
                    continue
                last_seq = seq
                self._reply({"t": "ack", "seq": seq})
            elif kind == "bye":
                front.publish({"kind": "bye", "session": session})
                self._reply({"t": "ack", "seq": last_seq})
                return
            else:
                self._reply({"t": "err", "why": f"unknown kind {kind!r}"})
                return

    def _reply(self, obj: dict) -> None:
        try:
            self.wfile.write(
                json.dumps(obj, separators=(",", ":")).encode() + b"\n")
        except OSError:
            pass  # peer vanished mid-reply: its redelivery is idempotent


class _Server(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True
    owner: "IngestFront"


class IngestFront:  # graftlint: state=session states=new,open,closed,dropped edges=new->open,open->closed,open->dropped
    """The sessioned op-intake server (module docstring has the wire
    and confinement contracts).

    Hot surface: :meth:`drain` / :meth:`churn` / :attr:`idle` (all
    non-blocking).  Handler surface: :meth:`publish` → the declared
    ``publish=ingest`` point.  Driver surface: :meth:`start` /
    :meth:`stop`.
    """

    def __init__(self, valid_docs, tenant_names=("default",), *,
                 capacity: int = DEFAULT_CAPACITY,
                 put_timeout: float = 2.0, max_frame: int = 1 << 16,
                 pace_slack: int = 2):
        # immutable views: written once here (before any handler thread
        # exists), read by every handler — the G014-legal shape
        self.valid_docs = frozenset(valid_docs)
        self.tenant_names = frozenset(tenant_names)
        self.put_timeout = float(put_timeout)
        self.max_frame = int(max_frame)
        self.pace_slack = int(pace_slack)
        #: the hot clock, published to handlers like churn_gen (an
        #: immutable int swap).  Frames whose planned round is further
        #: than ``pace_slack`` ahead get a ``retry`` — the wire itself
        #: enforces the open-loop arrival process, and connections stay
        #: live across the drain horizon (what conn_churn drops).
        self.now = 0
        self._q: queue.Queue = queue.Queue(maxsize=max(8, int(capacity)))
        self._srv: _Server | None = None
        self._thread: threading.Thread | None = None
        self.port: int | None = None
        #: churn generation: bumped by the hot thread (immutable int
        #: swap, no publish point needed), compared by handlers
        self.churn_gen = 0
        # hot-owned counters (tallied in drain(), never by handlers)
        self.frames = 0
        self.ops_frames = 0
        self.ops_delivered = 0
        self.bad_frames = 0
        self.sessions_opened = 0
        self.sessions_resumed = 0
        self.sessions_closed = 0
        self.churn_drops = 0
        # the session machine's legal graph, mirrored from the class
        # marker (G022/G025).  Edges are counted UNKEYED: a resumed
        # session re-enters new->open under the same name, and the
        # handler threads race the pump — per-instance sequencing
        # belongs to the client protocol (seq numbers), not this model.
        lifecycle.declare_machine(
            "session", ("new", "open", "closed", "dropped"),
            (("new", "open"), ("open", "closed"), ("open", "dropped")),
        )

    # ---- driver-side lifecycle (G013: never constructed mid-drain) --

    def start(self) -> int:  # graftlint: acquire=socket
        if self._srv is not None:
            return self.port  # type: ignore[return-value]
        srv = _Server(("127.0.0.1", 0), _IngestHandler)
        srv.owner = self
        self._srv = srv
        self.port = srv.server_address[1]
        self._thread = threading.Thread(
            target=srv.serve_forever, name="serve-ingest", daemon=True,
            kwargs={"poll_interval": 0.05},
        )
        self._thread.start()
        lifecycle.acquire("socket", id(self))
        return self.port

    def stop(self) -> None:  # graftlint: release=socket
        if self._srv is None:
            return
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._srv = None
        self._thread = None
        lifecycle.release("socket", id(self))

    # ---- handler surface (the ingest thread) ----

    def publish(self, payload: dict, timeout: float | None = None
                ) -> bool:  # graftlint: thread=ingest
        """Hand one payload to the hot pump.  Control payloads use the
        short default timeout; ``ops`` frames pass the configured
        backpressure timeout and report ``False`` on a full queue so
        the handler can turn it into a client ``retry``."""
        try:
            self._publish(payload, 1.0 if timeout is None else timeout)
        except queue.Full:
            return False
        return True

    @published
    def _publish(self, payload: dict, timeout: float) -> None:  # graftlint: publish=ingest  # graftlint: thread=ingest
        """THE declared swap point: one frame's payload leaves the
        ingest thread.  ``share`` stamps it with this point's publish
        generation (armed runs), and the bounded ``put`` means a
        stalled pump surfaces as client backpressure, never as an
        unbounded buffer."""
        self._q.put(share(payload, "IngestFront.delivery"),
                    timeout=timeout)

    # ---- hot-thread surface (non-blocking by contract, G016) ----

    @property
    def idle(self) -> bool:
        return self._q.empty()

    def churn(self) -> None:  # graftlint: thread=hot
        """Drop every live connection at its next frame (the
        ``conn_churn`` chaos fault).  An immutable int swap — handlers
        poll the generation, no lock, no publish point (the
        ``set_health`` pattern)."""
        self.churn_gen = self.churn_gen + 1

    def drain(self) -> list[dict]:  # graftlint: thread=hot  # graftlint: transition=session:new->open,open->closed,open->dropped
        """Harvest every pending payload (never blocks).  Each one
        passes the ``reveal`` gate — the reader side of the publish
        contract — and all counters are tallied here, on the hot
        thread that owns them.  Session edges are counted here too
        (hot side, after the crossing) so the artifact's lifecycle
        block attributes every open/close/drop."""
        out: list[dict] = []
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            payload = reveal(item)
            self.frames += 1
            kind = payload.get("kind")
            if kind == "ops":
                self.ops_frames += 1
                self.ops_delivered += payload.get("count", 0)
            elif kind == "hello":
                self.sessions_opened += 1
                if payload.get("resume"):
                    self.sessions_resumed += 1
                lifecycle.transition("session", "new", "open")
            elif kind == "bye":
                self.sessions_closed += 1
                lifecycle.transition("session", "open", "closed")
            elif kind == "bad_frame":
                self.bad_frames += 1
            elif kind == "churn_drop":
                self.churn_drops += 1
                lifecycle.transition("session", "open", "dropped")
            out.append(payload)
        return out

    def status_fields(self) -> dict:
        """Hot-owned gauges for /status.json and the artifact."""
        return {
            "port": self.port,
            "frames": self.frames,
            "ops_frames": self.ops_frames,
            "ops_delivered": self.ops_delivered,
            "bad_frames": self.bad_frames,
            "sessions_opened": self.sessions_opened,
            "sessions_resumed": self.sessions_resumed,
            "sessions_closed": self.sessions_closed,
            "churn_drops": self.churn_drops,
            "queue_depth": self._q.qsize(),
        }
