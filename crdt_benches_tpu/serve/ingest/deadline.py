"""Deadline/SLO-aware selection — ``DeadlineScheduler``.

``FleetScheduler._select`` walks a round-robin rotation; that is the
right fairness policy for a batch drain but the wrong one for serving
under latency budgets: a doc admitted with 8 rounds of budget left
should not wait behind one with 80.  ``DeadlineScheduler`` re-sorts
the rotation into earliest-deadline-first order before every
selection pass and otherwise reuses the base selection verbatim —
per-class lane bounds, bounded-queue deferral, dup clamping, request
contexts, and the macro-round staging downstream are all untouched.

A doc's deadline is static: ``arrival + budget(capacity class)``,
with per-class budgets in rounds (the same capacity classes
``obs/slo.py`` keys its burn windows on).  Draining by the deadline
counts as met, after it as missed; both totals ride /status.json and
the artifact's ``ingest`` block.

The subclass also hosts the open-loop glue the base class should not
know about: an optional ``ingest_status`` callable merged into
``status_fields()`` so the live front's gauges reach /status.json
without the bench driver patching scheduler internals.

EDF can be disarmed (``edf=False``) — the open-loop family always
drives this class for the status/deadline plumbing, while
``--serve-deadline`` is what flips selection from round-robin to EDF.
"""

from collections import deque

from ..scheduler import DocStream, FleetScheduler

__all__ = ["DeadlineScheduler", "DEFAULT_DEADLINE_BUDGET"]

#: rounds of latency budget for classes without an explicit entry —
#: generous enough that a closed-loop drain of a small fleet meets it.
DEFAULT_DEADLINE_BUDGET = 64


class DeadlineScheduler(FleetScheduler):
    """EDF selection over per-class latency budgets.

    ``deadline_budgets`` maps capacity class (row length) to a budget
    in macro-rounds; anything unlisted gets ``default_budget``.
    """

    def __init__(self, pool, streams, *, edf: bool = True,
                 deadline_budgets: dict[int, int] | None = None,
                 default_budget: int = DEFAULT_DEADLINE_BUDGET, **kw):
        super().__init__(pool, streams, **kw)
        self._edf = bool(edf)
        self._budgets = dict(deadline_budgets or {})
        self._default_budget = int(default_budget)
        self._deadlines: dict[int, int] = {}
        self.deadline_met = 0
        self.deadline_missed = 0
        #: optional () -> dict merged into status_fields()["ingest"];
        #: set by the open-loop driver before the drain starts.
        self.ingest_status = None

    def deadline_for(self, doc_id: int) -> int:
        """Absolute round this doc must drain by (cached — arrival and
        capacity class are both static)."""
        dl = self._deadlines.get(doc_id)
        if dl is None:
            st = self.streams[doc_id]
            rec = self.pool.docs[doc_id]
            cls = self.pool.class_for(max(rec.length, 1))
            budget = self._budgets.get(cls, self._default_budget)
            dl = st.arrival + budget
            self._deadlines[doc_id] = dl
        return dl

    def _select(self, plan) -> None:
        """EDF re-sort, then the base selection pass.  The base
        rotation discipline (scheduled to the back, deferred in place)
        is irrelevant here — the rotation is re-sorted every round, so
        urgency always wins over recency."""
        if self._edf and len(self._rr) > 1:
            self._rr = deque(sorted(
                self._rr,
                key=lambda d: (self.deadline_for(d),
                               self.streams[d].arrival, d),
            ))
        super()._select(plan)

    def _note_doc_drained(self, st: DocStream, tag: str | None = None
                          ) -> None:
        """Score the deadline before the base close (which adds the doc
        to ``_ended`` — the guard that keeps re-entries from double
        counting)."""
        if st.doc_id not in self._ended:
            if self.round <= self.deadline_for(st.doc_id):
                self.deadline_met += 1
            else:
                self.deadline_missed += 1
        super()._note_doc_drained(st, tag)

    def deadline_fields(self) -> dict:
        met, missed = self.deadline_met, self.deadline_missed
        total = met + missed
        return {
            "edf": self._edf,
            "default_budget": self._default_budget,
            "budgets": {str(k): v for k, v in sorted(self._budgets.items())},
            "met": met,
            "missed": missed,
            "hit_rate": round(met / total, 4) if total else 1.0,
        }

    def status_fields(self) -> dict:
        out = super().status_fields()
        out["deadline"] = self.deadline_fields()
        if self.ingest_status is not None:
            out["ingest"] = self.ingest_status()
        return out
