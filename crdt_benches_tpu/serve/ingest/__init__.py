"""Live ingest front door — the subsystem that turns the serve stack
from a batch replayer into a server.

Four pieces, each its own module:

- :mod:`.front` — a thread-confined TCP front (sibling of
  ``obs/status.py``'s HTTP server) accepting CRC-framed op batches on
  per-session connections.  Handler threads are ``thread=ingest``;
  the ONLY mutable crossing into the hot drain is the declared
  ``publish=ingest`` swap point (G013–G017 gated, race-sanitized).
- :mod:`.admission` — per-tenant admission control: token buckets,
  per-tenant queue budgets, and SLO-aware admit/defer/shed driven by
  the class burn rates ``obs/slo.py`` already tracks.  Sheds are
  journaled with the exact record shape the existing overflow sheds
  use, so ``recover_fleet`` replays them with zero new code.
- :mod:`.deadline` — ``DeadlineScheduler``, a ``FleetScheduler``
  subclass replacing round-robin selection with earliest-deadline-
  first over per-class latency budgets; macro-round staging is
  untouched.
- :mod:`.loadgen` — the open-loop load family (bench ids
  ``serve/open/<mix>/<fleet>``): seeded Poisson/burst arrival
  processes at a configured offered load, the hot-side pump that
  marries the front to the scheduler's bounded queues, and the
  offered-load sweep that emits the p99-vs-utilization knee curve.
"""

from .admission import (AdmissionController, TenantPolicy,
                        TenantSpecError, parse_tenant_spec)
from .deadline import DeadlineScheduler
from .front import FRAME_KINDS, IngestFront, decode_frame, encode_frame
from .loadgen import (IngestPump, OpenLoadClient, OpenLoadPlan,
                      build_open_plan, drive_open_loop, parse_open_spec)

__all__ = [
    "AdmissionController",
    "TenantPolicy",
    "TenantSpecError",
    "parse_tenant_spec",
    "DeadlineScheduler",
    "IngestFront",
    "FRAME_KINDS",
    "encode_frame",
    "decode_frame",
    "IngestPump",
    "OpenLoadClient",
    "OpenLoadPlan",
    "build_open_plan",
    "drive_open_loop",
    "parse_open_spec",
]
