"""Exhaustive crash-point enumeration over the durability stack.

The headline capability of graftlint v4's runtime twin: drive a small
real fleet through EVERY declared durable protocol — WAL appends +
segment seals, delta/full snapshot barriers with hard-linked spool
members, crash-safe segment GC, spool evict/rehydrate churn, a live
reshard (manifest commit + journaled moves + read-witnessed retire),
and a flight-recorder dump — under ``lint/fs_sanitizer.py`` interposition,
record the complete mutating-op sequence, then re-run the whole
workload once per op with an :class:`InjectedCrash` at exactly that
boundary and require **byte-verified recovery** at every single
injection point: ``recover_fleet`` into a fresh pool, resume through
the normal macro-round path, and every document decodes to the oracle
replay.  The workload is deterministic (seeded synth streams, no
wall-clock dependence in the fs path), so crash pass ``i`` observes
the same op sequence the recording pass did.

This is the dynamic proof of the G018/G019 static model: if any
ordering in the stack were wrong — an unlink before its install, a
rename whose directory entry a recovery depends on, a torn GC pass —
some boundary in the enumeration would recover to the wrong bytes or
not at all.  The per-protocol point counts are asserted NONZERO so the
harness can never silently cover nothing.  The fleet is sharded
(``shards=2``) with a ``drain:1`` reshard armed, so every boundary
also proves the shard-partition invariant: after recovery each doc
exists on exactly one non-retired shard
(:func:`serve.reshard.check_shard_partition`).

Runs as a tier-1 test (tests/test_fs_sanitizer.py) and as the
``serve-longhaul`` smoke's fs leg::

    JAX_PLATFORMS=cpu python -m crdt_benches_tpu.serve.fscrash
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile

from ..lint import fs_sanitizer
from ..obs.flight import FlightRecorder
from ..oracle.text_oracle import replay_trace
from .journal import OpJournal, recover_fleet
from .pool import DocPool
from .reshard import (
    ReshardCoordinator,
    check_shard_partition,
    parse_reshard_spec,
)
from .scheduler import FleetScheduler, prepare_streams
from .workload import build_fleet

#: Tiny but protocol-complete: two capacity classes, a 3-row device
#: budget against the fleet (forced evict/rehydrate churn = spool
#: protocol), barriers every 2 rounds with a full re-root every 2nd
#: barrier (delta chains + member adoption), sub-KiB WAL segments
#: (seals + GC victims), and a flight dump at drain end.  The default
#: config is the smoke's (~80 boundaries); ``small=True`` shrinks the
#: streams for the tier-1 test while keeping every protocol covered.
_BANDS = {
    "synth-small": ("synth", (10, 60)),
    "synth-medium": ("synth", (150, 360)),
}
_MIX = {"synth-small": 0.7, "synth-medium": 0.3}
_SMALL_BANDS = {"synth-small": ("synth", (8, 36))}
_SMALL_MIX = {"synth-small": 1.0}
_CLASSES = (256, 1024)
_SLOTS = (2, 2)  # % _SHARDS == 0: one row of each class per shard
_SHARDS = 2
_RESHARD = "drain:1@0,of=2,batch=2"  # begins on the first round
_DOCS = 5
_SEED = 11
_BATCH = 16
_CHARS = 64
_MACRO_K = 2


def _sessions(small: bool = False):
    if small:
        return build_fleet(4, mix=_SMALL_MIX, seed=_SEED,
                           arrival_span=1, bands=_SMALL_BANDS)
    return build_fleet(_DOCS, mix=_MIX, seed=_SEED, arrival_span=2,
                       bands=_BANDS)


def _drain(base: str, small: bool = False) -> None:
    """One full protocol workload under ``base``: journaled drain to
    completion + a flight dump.  Raises :class:`InjectedCrash` midway
    when a crash point is armed."""
    jd = os.path.join(base, "journal")
    sp = os.path.join(base, "spool")
    fl = os.path.join(base, "flight")
    fs_sanitizer.clear_watch_roots()  # each pass owns fresh dirs
    fs_sanitizer.watch_root(jd)
    fs_sanitizer.watch_root(sp)
    fs_sanitizer.watch_root(fl)
    sessions = _sessions(small)
    pool = DocPool(classes=_CLASSES, slots=_SLOTS, spool_dir=sp,
                   shards=_SHARDS)
    streams = prepare_streams(sessions, pool, batch=_BATCH,
                              batch_chars=_CHARS)
    journal = OpJournal(jd, segment_bytes=128 if small else 192)
    reshard = ReshardCoordinator(
        pool, journal, parse_reshard_spec(_RESHARD)
    )
    sched = FleetScheduler(
        pool, streams, batch=_BATCH, macro_k=_MACRO_K,
        batch_chars=_CHARS, journal=journal, reshard=reshard,
        snapshot_every=2, snapshot_full_every=2,
    )
    try:
        sched.run()
        flight = FlightRecorder(os.path.join(fl, "dump.json"), ring=8)
        flight.note_round({"round": sched.round, "seconds": 0.0})
        flight.trigger("fscrash-probe")
    finally:
        journal.close()


def _recover_and_verify(base: str, small: bool = False) -> None:
    """Recovery after a (possibly crashed) drain: fresh pool + streams,
    ``recover_fleet``, resume through the normal macro-round path, and
    byte-verify every document against the oracle replay."""
    jd = os.path.join(base, "journal")
    sessions = _sessions(small)
    pool = DocPool(classes=_CLASSES, slots=_SLOTS,
                   spool_dir=os.path.join(base, "spool_recover"),
                   shards=_SHARDS)
    streams = prepare_streams(sessions, pool, batch=_BATCH,
                              batch_chars=_CHARS)
    rep = recover_fleet(pool, streams, jd)
    # the shard-partition invariant holds at EVERY crash boundary: the
    # recovered map has each doc on exactly one shard, none on a
    # retired one — whether the crash tore the reshard (rolled
    # forward), preceded it (rolled back) or followed its commit
    problems = check_shard_partition(pool)
    if problems:
        raise AssertionError(
            "post-recovery shard partition violated (reshard "
            f"{'completed' if rep.reshard_completed else 'torn/absent'},"
            f" retired {rep.reshard_retired}): " + "; ".join(problems)
        )
    FleetScheduler(
        pool, streams, batch=_BATCH, macro_k=_MACRO_K,
        batch_chars=_CHARS, start_round=rep.resume_round,
    ).run()
    problems = check_shard_partition(pool)
    if problems:
        raise AssertionError(
            "post-resume shard partition violated: "
            + "; ".join(problems)
        )
    for s in sessions:
        got = pool.decode(s.doc_id)
        want = replay_trace(s.trace)
        if got != want:
            raise AssertionError(
                f"doc {s.doc_id}: post-recovery bytes diverge from the "
                f"oracle (snapshot round {rep.snapshot_round}, "
                f"{rep.chain_fallbacks} fallbacks)"
            )


def enumerate_crash_points(workdir: str | None = None,
                           log=lambda s: None,
                           small: bool = False) -> dict:
    """The full enumeration.  Returns a report dict::

        {"mutations": M, "per_protocol": {tag: n}, "verified": M}

    - recording pass: run the workload armed, capture the mutating-op
      count ``M`` and its per-protocol attribution (every declared
      protocol must have contributed at least one point);
    - for each ``i`` in ``range(M)``: fresh directories, crash at
      boundary ``i`` (the op raises instead of executing and the fs
      freezes — a dead process writes nothing), then recover + resume
      + byte-verify against the oracle.
    """
    own = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="crdt_fscrash_")
    try:
        record_dir = os.path.join(workdir, "record")
        os.makedirs(record_dir)
        fs_sanitizer.reset_counters()
        fs_sanitizer._arm()
        try:
            _drain(record_dir, small)
        finally:
            if not fs_sanitizer.sanitizing():
                fs_sanitizer.disarm()
        counts = fs_sanitizer.counters()
        m = fs_sanitizer.mutation_count()
        per_protocol = {
            tag: sum(n for op, n in ops.items()
                     if op in fs_sanitizer.MUTATING_OPS)
            for tag, ops in counts["ops"].items()
        }
        # the recording pass must also recover clean (crash "after the
        # last op" — the trivial boundary)
        _recover_and_verify(record_dir, small)
        for tag in fs_sanitizer.KNOWN_PROTOCOLS:
            if per_protocol.get(tag, 0) <= 0:
                raise AssertionError(
                    f"protocol `{tag}` contributed no mutating op — "
                    "the enumeration would silently not cover it: "
                    f"{per_protocol}"
                )
        if counts["unattributed"]:
            raise AssertionError(
                "unattributed mutating ops in the recording pass: "
                f"{counts['unattributed']}"
            )
        log(f"fscrash: {m} crash points "
            + ", ".join(f"{t}={n}" for t, n in sorted(per_protocol.items())))
        verified = 0
        for i in range(m):
            base = os.path.join(workdir, f"crash_{i:04d}")
            os.makedirs(base)
            crashed = False
            try:
                with fs_sanitizer.crash_at(i):
                    _drain(base, small)
            except fs_sanitizer.InjectedCrash:
                crashed = True
            if not crashed:
                raise AssertionError(
                    f"crash point {i} never fired (expected {m} "
                    "mutating ops — nondeterministic op sequence?)"
                )
            _recover_and_verify(base, small)
            verified += 1
            shutil.rmtree(base, ignore_errors=True)  # bound disk use
        log(f"fscrash: {verified}/{m} crash points recovered "
            "byte-verified")
        return {
            "mutations": m,
            "per_protocol": per_protocol,
            "verified": verified,
        }
    finally:
        if own:
            shutil.rmtree(workdir, ignore_errors=True)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    small = "--small" in argv
    if [a for a in argv if a != "--small"]:
        print("usage: python -m crdt_benches_tpu.serve.fscrash "
              "[--small]", file=sys.stderr)
        return 2
    report = enumerate_crash_points(
        log=lambda s: print(s, flush=True), small=small,
    )
    ok = report["verified"] == report["mutations"] > 0
    print(
        f"fscrash: {'OK' if ok else 'FAILED'} — "
        f"{report['verified']}/{report['mutations']} boundaries "
        f"byte-verified, per-protocol {report['per_protocol']}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
