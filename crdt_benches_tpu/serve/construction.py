"""Construction-cost accounting: RSS probes + the fleet-size scaler.

The streaming-construction work (serve/workload.py ``FleetSpec`` +
serve/scheduler.py ``LazyStreams``) claims setup cost and host
footprint scale with the ACTIVE set, not the fleet.  This module is
how the claim is measured and committed:

- :func:`current_rss_bytes` / :func:`peak_rss_bytes` — the two RSS
  probes the bench embeds in every artifact's ``construction`` block
  (``VmRSS`` point-in-time from ``/proc/self/status``; ``ru_maxrss``
  high-water mark from ``getrusage``);
- :func:`probe` — build ONE fleet to scheduler-ready (spec/sessions →
  pool → streams → scheduler, NO drain) and report construction_ms +
  RSS, in either mode;
- :func:`scaling_table` — the fleet-size-vs-RSS table.  ``ru_maxrss``
  is process-monotonic, so each (size, mode) cell runs :func:`probe`
  in a FRESH subprocess (``python -m crdt_benches_tpu.serve
  .construction``) and parses its one-line JSON; eager rows are capped
  at ``eager_limit`` docs (past it the eager build takes minutes —
  that being the point of the table).

The table rides the artifact (``construction.scaling``) via the
runner's ``--serve-stream-scaling`` flag, and ``tools/bench_compare.py``
gates ``construction_ms`` / ``peak_rss_bytes`` against the committed
baseline (skip-with-note when either side predates the block).
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import subprocess
import sys
import time

_PAGE = resource.getpagesize()


def current_rss_bytes() -> int:
    """Point-in-time resident set size of THIS process, in bytes.

    Linux: ``VmRSS`` from ``/proc/self/status`` (what the fleet holds
    *right now* — the number the scaling table plots).  Elsewhere:
    falls back to the ``ru_maxrss`` high-water mark."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return peak_rss_bytes()


def peak_rss_bytes() -> int:
    """Process-lifetime peak RSS in bytes (``ru_maxrss``; KiB on
    Linux).  Monotonic per process — comparable across runs only when
    each run is its own process, which is why :func:`scaling_table`
    shells a fresh interpreter per cell."""
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def probe(
    n_docs: int,
    *,
    mix: str = "mixed",
    seed: int = 0,
    arrival_span: int = 8,
    arrival_dist: str = "uniform",
    serve_tiers: str | None = None,
    stream: bool = True,
    batch: int = 64,
    batch_chars: int = 256,
    classes=(256, 1024, 4096, 8192, 49152),
    slots=(2048, 512, 128, 32, 16),
) -> dict:
    """Build one fleet to scheduler-ready and report the cost — the
    construction half of a serve run, with NO drain.  Lazy mode builds
    ``FleetSpec`` + ``LazyStreams`` (every doc in genesis); eager mode
    is the historic ``build_fleet`` + ``prepare_streams`` path."""
    # lazy imports: bench.py imports this module's RSS probes at its
    # own import time, so importing bench at OUR top would be a cycle
    from .bench import parse_tier_spec
    from .pool import DocPool
    from .scheduler import FleetScheduler, LazyStreams, prepare_streams
    from .workload import FleetSpec, build_fleet

    warm_docs = 0
    if serve_tiers:
        slots, warm_docs = parse_tier_spec(serve_tiers, slots)
    rss0 = current_rss_bytes()
    pool = None
    t0 = time.perf_counter()
    try:
        if stream:
            spec = FleetSpec.build(
                n_docs, mix=mix, seed=seed, arrival_span=arrival_span,
                arrival_dist=arrival_dist,
            )
            pool = DocPool(classes=classes, slots=slots,
                           warm_docs=warm_docs)
            streams = LazyStreams(
                spec, pool, batch=batch, batch_chars=batch_chars
            )
        else:
            sessions = build_fleet(
                n_docs, mix=mix, seed=seed, arrival_span=arrival_span,
                arrival_dist=arrival_dist,
            )
            pool = DocPool(classes=classes, slots=slots,
                           warm_docs=warm_docs)
            streams = prepare_streams(
                sessions, pool, batch=batch, batch_chars=batch_chars
            )
        sched = FleetScheduler(
            pool, streams, batch=batch, batch_chars=batch_chars
        )
        ms = (time.perf_counter() - t0) * 1e3
        assert not sched.done or n_docs == 0
        return {
            "n_docs": int(n_docs),
            "mode": "stream" if stream else "eager",
            "construction_ms": ms,
            "rss_before_bytes": rss0,
            "rss_after_bytes": current_rss_bytes(),
            "peak_rss_bytes": peak_rss_bytes(),
            "genesis_docs": pool.genesis_docs,
        }
    finally:
        if pool is not None:
            pool.close()


def scaling_table(
    sizes,
    *,
    mix: str = "mixed",
    seed: int = 0,
    arrival_span: int = 8,
    arrival_dist: str = "uniform",
    serve_tiers: str | None = None,
    eager_limit: int = 65536,
    timeout: float = 900.0,
    log=print,
) -> list[dict]:
    """One fresh-subprocess :func:`probe` per (size, mode) cell.

    Stream rows cover every requested size; eager contrast rows stop at
    ``eager_limit`` docs (0 disables them).  A cell that fails or times
    out becomes an ``{"error": ...}`` row — the table never lies by
    omission about a size that would not build."""
    rows: list[dict] = []
    for n in sorted({int(s) for s in sizes}):
        for mode in ("stream", "eager"):
            if mode == "eager" and (not eager_limit or n > eager_limit):
                continue
            cmd = [
                sys.executable, "-m",
                "crdt_benches_tpu.serve.construction",
                "--n-docs", str(n), "--mode", mode,
                "--mix", mix, "--seed", str(seed),
                "--arrival-span", str(arrival_span),
                "--arrival-dist", arrival_dist,
            ]
            if serve_tiers:
                cmd += ["--serve-tiers", serve_tiers]
            env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
                "JAX_PLATFORMS", "cpu"))
            try:
                out = subprocess.run(
                    cmd, capture_output=True, text=True,
                    timeout=timeout, env=env,
                )
            except subprocess.TimeoutExpired:
                rows.append({"n_docs": n, "mode": mode,
                             "error": f"timeout after {timeout:g}s"})
                log(f"construction: {mode}/{n} TIMED OUT")
                continue
            if out.returncode != 0:
                tail = (out.stderr or out.stdout or "").strip()
                rows.append({"n_docs": n, "mode": mode,
                             "error": tail[-400:] or "nonzero exit"})
                log(f"construction: {mode}/{n} FAILED")
                continue
            row = json.loads(out.stdout.strip().splitlines()[-1])
            rows.append(row)
            log(
                f"construction: {mode}/{n} — "
                f"{row['construction_ms']:.0f}ms, "
                f"peak rss {row['peak_rss_bytes'] / 2**20:.0f} MiB"
            )
    return rows


def main(argv=None) -> int:
    """``python -m crdt_benches_tpu.serve.construction``: one probe,
    one JSON line on stdout (the :func:`scaling_table` cell worker)."""
    ap = argparse.ArgumentParser(
        description="construction-cost probe (one fleet, no drain)"
    )
    ap.add_argument("--n-docs", type=int, required=True)
    ap.add_argument("--mode", choices=("stream", "eager"),
                    default="stream")
    ap.add_argument("--mix", default="mixed")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--arrival-span", type=int, default=8)
    ap.add_argument("--arrival-dist", default="uniform")
    ap.add_argument("--serve-tiers", default=None)
    args = ap.parse_args(argv)
    row = probe(
        args.n_docs, mix=args.mix, seed=args.seed,
        arrival_span=args.arrival_span, arrival_dist=args.arrival_dist,
        serve_tiers=args.serve_tiers, stream=args.mode == "stream",
    )
    print(json.dumps(row))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
