"""Deterministic fault injection for the serving engine.

A :class:`FaultPlan` is a seeded schedule of fault events parsed from a
compact spec string (the ``--serve-faults`` grammar); a
:class:`FaultInjector` is its runtime half — the scheduler polls hooks
at fixed points of every macro-round and the injector fires each event
exactly once, deterministically.  Everything is seeded: the same spec +
workload seed reproduces the same faults at the same rounds against the
same targets, so a chaos run is as replayable as a clean one.

Spec grammar (comma-separated ``key=value`` tokens)::

    seed=7,span=8,spool_corrupt=1,device_loss=1,queue_overflow=1

- ``seed``  — RNG seed for fire rounds / target picks (default 0)
- ``span``  — random fire rounds are drawn from ``[2, span]`` macro-
  rounds (default 8; events whose round never arrives before the drain
  ends are reported as not fired)
- ``stall_ms`` — host stall duration (default 40)
- ``burst``    — queue-overflow burst size in ops (default 4x the cap)
- fault kinds, each with an event count (``kind=N``) or an explicit
  fire round (``kind@round=N``):

  =================  ======================================================
  ``spool_corrupt``  flip bytes inside an existing eviction spool .npz
  ``spool_truncate`` truncate an existing spool to ~60% of its bytes
  ``device_loss``    clobber one capacity class's device state right
                     after a macro dispatch (mid-macro-round loss)
  ``dup_batch``      redeliver an op batch the doc already applied
                     (duplicated/reordered delivery; the cursor
                     high-water mark must drop it)
  ``stall``          sleep the host staging path for ``stall_ms``
  ``queue_overflow`` burst-deliver past a doc's bounded queue cap,
                     forcing an explicit shed/defer decision
  ``poison_rebuild`` make the targeted doc's rebuild fail (tests the
                     quarantine path; normally test-constructed)
  ``crash_compact``  kill the WAL segment GC pass mid-flight — between
                     its crash-safe manifest write and the unlinks
                     (journal mode only); the torn pass must be
                     completed by the next barrier, open, or recovery
  ``delta_corrupt``  flip bytes inside the newest delta snapshot's
                     member (journal mode with delta barriers only);
                     recovery must fall back down the CRC chain and
                     still byte-verify against the oracle
  ``replica_partition`` drop one replica's broadcast deliveries for a
                     span of rounds (serve/replicate/ only): the
                     replica's divergence window grows while its
                     writer-group peers advance, and the bus's
                     heal-time backlog flush must reconverge it
                     (``param`` = partition span in rounds, default 3)
  ``merge_reorder``  deliver one round's remote broadcast batches in a
                     permuted writer order (serve/replicate/ only);
                     sequence-keyed reassembly makes delivery order
                     commute, so byte-verify must stay green
  ``tier_evict_pressure`` force warm-tier churn under load (tiered
                     pool only): LRU warm entries are demoted to the
                     compressed cold spool mid-drain, so following
                     admissions pay the cold path (``param`` = entries
                     demoted, default half the tier)
  ``prefetch_miss``  drop one round's planned prefetch batch (tiered
                     pool only): the rehydrates never start, admission
                     takes the synchronous cold path and must stay
                     verify-green — the prefetcher is opportunism,
                     never a dependency
  ``conn_churn``     drop every live ingest connection at its next
                     frame (open-loop front only): clients must
                     reconnect-and-resume, and the idempotent delivery
                     high-water mark must absorb any redelivery —
                     recovery is a resumed session delivering ops
                     again
  ``tenant_flood``   one tenant's offered load is treated as inflated
                     by ``param``x (default 8) for a fixed window of
                     macro-rounds
                     (open-loop front only): admission must defer/shed
                     the flooder while other tenants keep admitting —
                     recovery is the flood window closing with the
                     pressure absorbed
  ``reshard_crash``  kill the reshard coordinator at its worst window:
                     AFTER the migration-manifest commit, BEFORE the
                     first per-doc move (reshard runs only): the next
                     round's tick (or ``recover_fleet``'s roll-forward)
                     must complete the reshard from the manifest alone
                     — recovery is the resumed coordinator committing
  =================  ======================================================

Every event records whether it fired and whether the engine recovered
from it; the bench artifact carries the full event list, and the chaos
smoke exits nonzero when any event goes unfired or unrecovered.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

from ..obs.trace import instant

KINDS = (
    "spool_corrupt",
    "spool_truncate",
    "device_loss",
    "dup_batch",
    "stall",
    "queue_overflow",
    "poison_rebuild",
    "crash_compact",
    "delta_corrupt",
    "replica_partition",
    "merge_reorder",
    "tier_evict_pressure",
    "prefetch_miss",
    "conn_churn",
    "tenant_flood",
    "reshard_crash",
)

#: Kinds that need the write-ahead journal armed (``--serve-journal``):
#: they target the durability subsystem itself — a journal-less drain
#: never reaches their injection points, so ``run_serve_bench`` rejects
#: the combination up front instead of failing the chaos gate with a
#: confusing not_fired at drain end.
JOURNAL_KINDS = ("crash_compact", "delta_corrupt")

#: Kinds only the replicated scheduler (serve/replicate/) polls.  A
#: plain serve drain never fires them, so ``run_serve_bench`` rejects a
#: spec that arms them without ``--serve-writers`` up front — a loud
#: configuration error instead of a whole drain ending in a confusing
#: not_fired chaos-gate failure.
REPLICATION_KINDS = ("replica_partition", "merge_reorder")

#: Kinds that need the tiered pool (``--serve-tiers`` / warm_docs > 0):
#: they target the warm tier and the prefetcher — a two-tier drain
#: never reaches their injection points, so ``run_serve_bench`` rejects
#: the combination up front instead of ending in a confusing not_fired.
TIER_KINDS = ("tier_evict_pressure", "prefetch_miss")

#: Kinds only the open-loop ingest pump polls (``--serve-open``): they
#: target the live front and the admission controller — a closed-loop
#: replay has neither, so ``run_serve_bench`` rejects a spec that arms
#: them without the open-loop family up front instead of ending in a
#: confusing not_fired chaos-gate failure.
INGEST_KINDS = ("conn_churn", "tenant_flood")

#: Kinds only the reshard coordinator polls (``--serve-reshard``): they
#: target the live-migration state machine — a static-topology drain
#: never reaches the injection point, so ``run_serve_bench`` rejects a
#: spec that arms them without a reshard up front instead of ending in
#: a confusing not_fired chaos-gate failure.  (The reshard itself also
#: requires the journal: the manifest lives in the journal dir.)
RESHARD_KINDS = ("reshard_crash",)


@dataclass
class FaultEvent:
    kind: str
    round: int  # earliest macro-round the event may fire
    target: int | None = None  # doc id (or class) pin; None = pick live
    param: int = 0  # stall ms / burst ops / dup depth (0 = default)
    fired: bool = False
    fired_round: int = -1
    recovered: bool = False
    detail: dict = field(default_factory=dict)
    # per-kind fired/recovered counters, shared across a plan's events
    # (set by FaultInjector.bind_metrics; None outside an instrumented
    # drain)
    counters: dict | None = field(
        default=None, repr=False, compare=False
    )
    rec_counters: dict | None = field(
        default=None, repr=False, compare=False
    )

    def fire(self, rnd: int, **detail) -> None:
        self.fired = True
        self.fired_round = rnd
        self.detail.update(detail)
        if self.counters is not None:
            self.counters[self.kind].inc()
        # timeline marker (no-op unless span tracing is armed); the
        # constant event name keeps G012 happy — kind rides in args
        instant("serve.fault", kind=self.kind, round=rnd)

    def recover(self, **detail) -> None:
        """Mark the event recovered (idempotent) so per-kind recovery
        counters reach the registry — the status endpoint's fault/
        degraded view needs recoveries as a live series, not just the
        end-of-run summary."""
        if detail:
            self.detail.update(detail)
        if not self.recovered:
            self.recovered = True
            if self.rec_counters is not None:
                self.rec_counters[self.kind].inc()

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "round": self.round,
            "fired": self.fired,
            "fired_round": self.fired_round,
            "recovered": self.recovered,
            "target": self.target,
            "detail": self.detail,
        }


class FaultPlan:
    """A seeded, ordered fault schedule."""

    def __init__(self, events: list[FaultEvent], seed: int = 0,
                 stall_ms: int = 40, burst: int = 0, spec: str = ""):
        self.events = sorted(events, key=lambda e: (e.round, e.kind))
        self.seed = seed
        self.stall_ms = stall_ms
        self.burst = burst
        self.spec = spec

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        seed, span, stall_ms, burst = 0, 8, 40, 0
        counts: list[tuple[str, int | None, int]] = []  # (kind, round, n)
        for tok in str(spec).split(","):
            tok = tok.strip()
            if not tok:
                continue
            if "=" not in tok:
                raise ValueError(f"fault spec token {tok!r}: expected k=v")
            key, val = tok.split("=", 1)
            key, val = key.strip(), int(val)
            if key == "seed":
                seed = val
            elif key == "span":
                span = max(2, val)
            elif key == "stall_ms":
                stall_ms = val
            elif key == "burst":
                burst = val
            else:
                rnd = None
                if "@" in key:
                    key, at = key.split("@", 1)
                    rnd = int(at)
                if key not in KINDS:
                    raise ValueError(
                        f"fault spec: unknown kind {key!r} "
                        f"(expected one of {KINDS})"
                    )
                counts.append((key, rnd, val))
        rng = np.random.default_rng(seed)
        events = []
        for kind, rnd, n in counts:
            for _ in range(max(0, n)):
                r = rnd if rnd is not None else int(rng.integers(2, span + 1))
                events.append(FaultEvent(kind=kind, round=r))
        return cls(events, seed=seed, stall_ms=stall_ms, burst=burst,
                   spec=spec)

    def summary(self) -> dict:
        fired = [e for e in self.events if e.fired]
        return {
            "spec": self.spec,
            "seed": self.seed,
            "events": [e.to_dict() for e in self.events],
            "injected": len(fired),
            "recovered": sum(e.recovered for e in fired),
            "unrecovered": sum(not e.recovered for e in fired),
            "not_fired": sum(not e.fired for e in self.events),
        }


class FaultInjector:
    """The runtime half: the scheduler polls these hooks at fixed points
    of each macro-round; every pending event fires at the first poll at
    or after its scheduled round where a valid target exists."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.rng = np.random.default_rng(plan.seed ^ 0x9E3779B9)

    def bind_metrics(self, registry) -> None:
        """Pre-register fired/recovered counters per fault kind
        (constant names, built OFF the hot path) and hand the tables to
        every event so ``FaultEvent.fire``/``recover`` emit through the
        registry."""
        counters = {
            k: registry.counter("serve.faults.fired." + k) for k in KINDS
        }
        rec_counters = {
            k: registry.counter("serve.faults.recovered." + k)
            for k in KINDS
        }
        for e in self.plan.events:
            e.counters = counters
            e.rec_counters = rec_counters

    def _pending(self, rnd: int, *kinds: str) -> FaultEvent | None:
        for e in self.plan.events:
            if e.kind in kinds and not e.fired and rnd >= e.round:
                return e
        return None

    # ---- hooks (each returns the event to fire, or None) ----

    def stall_event(self, rnd: int) -> tuple[FaultEvent, float] | None:
        e = self._pending(rnd, "stall")
        if e is None:
            return None
        return e, (e.param or self.plan.stall_ms) / 1e3

    def overflow_event(self, rnd: int) -> FaultEvent | None:
        return self._pending(rnd, "queue_overflow")

    def reshard_crash_event(self, rnd: int) -> FaultEvent | None:
        """Polled by the reshard coordinator exactly once per reshard,
        in the window between the committed migration manifest and the
        first per-doc move — the worst crash point the recovery
        protocol must absorb."""
        return self._pending(rnd, "reshard_crash")

    def dup_event(self, rnd: int, doc_id: int,
                  cursor: int) -> FaultEvent | None:
        """A redelivered batch for ``doc_id``: only docs that already
        applied ops are meaningful dup targets."""
        if cursor <= 0:
            return None
        e = self._pending(rnd, "dup_batch")
        if e is None or (e.target is not None and e.target != doc_id):
            return None
        return e

    def device_loss_event(self, rnd: int, cls: int) -> FaultEvent | None:
        e = self._pending(rnd, "device_loss")
        if e is None or (e.target is not None and e.target != cls):
            return None
        return e

    def spool_event(self, rnd: int) -> FaultEvent | None:
        return self._pending(rnd, "spool_corrupt", "spool_truncate")

    def compact_crash_event(self, rnd: int) -> FaultEvent | None:
        """Kill the WAL GC pass between its manifest write and the
        unlinks (polled by the journal's crash hook at each barrier;
        pending until a pass actually has victims to delete)."""
        return self._pending(rnd, "crash_compact")

    def delta_corrupt_event(self, rnd: int) -> FaultEvent | None:
        """Flip bytes in the newest delta snapshot member (polled after
        each barrier; pending until a delta link exists)."""
        return self._pending(rnd, "delta_corrupt")

    def tier_pressure_event(self, rnd: int) -> FaultEvent | None:
        """Force warm-tier churn (polled each macro-round by the
        tiered scheduler; pending until the warm tier holds entries)."""
        return self._pending(rnd, "tier_evict_pressure")

    def prefetch_miss_event(self, rnd: int) -> FaultEvent | None:
        """Drop one round's planned prefetch batch (polled at prefetch
        planning; pending until a round actually plans prefetches)."""
        return self._pending(rnd, "prefetch_miss")

    def conn_churn_event(self, rnd: int) -> FaultEvent | None:
        """Drop every live ingest connection (polled by the open-loop
        pump each macro-round; the front's churn generation bump does
        the dropping)."""
        return self._pending(rnd, "conn_churn")

    def tenant_flood_event(self, rnd: int) -> FaultEvent | None:
        """Inflate one tenant's offered load by ``param``x for a fixed
        window (polled by the open-loop pump; admission must absorb
        the pressure)."""
        return self._pending(rnd, "tenant_flood")

    def partition_event(self, rnd: int) -> FaultEvent | None:
        """A replica's broadcast link drops for a span (polled by the
        replicated scheduler's bus tick; ``param`` = span rounds)."""
        return self._pending(rnd, "replica_partition")

    def reorder_event(self, rnd: int) -> FaultEvent | None:
        """One round's remote broadcast batches delivered in permuted
        writer order (polled by the replicated scheduler's bus tick)."""
        return self._pending(rnd, "merge_reorder")

    def poisoned(self, doc_id: int) -> bool:
        """Fire-once: is this doc's REBUILD poisoned?  (Exercises the
        quarantine path — recovery itself failing.)"""
        for e in self.plan.events:
            if e.kind == "poison_rebuild" and not e.fired and (
                e.target is None or e.target == doc_id
            ):
                e.fire(-1, doc=doc_id)
                e.recovered = False  # a poisoned rebuild ends in quarantine
                return True
        return False

    # ---- corruption primitives ----

    def corrupt_file(self, path: str, kind: str) -> dict:
        """Damage an on-disk checkpoint: truncate to ~60% or flip a run
        of bytes in the middle.  The damaged bytes land in a NEW file
        swapped over ``path`` (never an in-place mutation): snapshot
        barriers hard-link live spools on the immutability guarantee
        that every spool write goes through ``os.replace``, and fault
        injection must honor the same contract — the fault hits THIS
        file, not a committed snapshot member sharing its inode.
        Returns detail for the event record."""
        data = bytearray(open(path, "rb").read())
        size = len(data)
        if kind == "spool_truncate" or size < 64:
            keep = max(1, int(size * 0.6))
            data = data[:keep]
            detail = {"mode": "truncate", "bytes": size, "kept": keep}
        else:
            off = int(self.rng.integers(size // 4, max(size // 4 + 1,
                                                       size - 16)))
            for i in range(off, min(off + 8, size)):
                data[i] ^= 0xFF
            detail = {"mode": "bitflip", "bytes": size, "offset": off}
        tmp = path + ".fault"
        with open(tmp, "wb") as f:
            f.write(bytes(data))
        os.replace(tmp, path)
        return detail

    def pick(self, candidates: list[int]) -> int:
        """Seeded target selection among live candidates."""
        return int(candidates[int(self.rng.integers(len(candidates)))])
