"""Multi-tenant workload generation for the document fleet.

Interleaves the four real editing traces with ``traces/synth.py`` random
streams across N simulated sessions.  A full real trace needs up to
~260k slots — far beyond any pool class, and a serving fleet hosts many
small-to-medium docs, not one giant one — so real-trace sessions replay
a **folded prefix window**:

- leading patches that alone would blow the slot budget (rustcode opens
  with a 42k-char file paste, seph-blog1 with a 4k one) are *folded*
  into ``start_content`` via the oracle — init slots cost no unit ops,
  they materialize directly in the fresh document row;
- the following patches form the edit stream, truncated so the doc's
  total slot need (init chars + window inserts) fits the band's budget.

Positions stay exactly the original trace's, so the oracle replay of
the window over the folded start is byte-for-byte ground truth.

The **mix** is a weight table over size *bands*; each band pins a stream
source ("synth" with an op-count range, or a real-trace budget) so
documents land across every pool capacity class.  Sessions get a
staggered **arrival round**, modeling tenants joining a live server.

Each band also carries a **delivery burst** — how many ops a session's
producer pushes toward the fleet per scheduler round.  It only matters
when the scheduler runs with a bounded per-doc queue (``queue_cap``):
delivery past the cap is refused (backpressure) or shed, and the burst
is what makes that pressure realistic instead of all-ops-at-once.
``build_fleet(delivery="banded")`` turns it on; the default (None)
keeps the legacy everything-pre-delivered stream.

Real-trace windows are cached per (trace, band): all sessions of one
band edit the same template document (many users editing from a shared
starting point); synthetic sessions are all distinct (seeded per doc).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

from ..oracle.text_oracle import OracleDocument
from ..traces.loader import TRACES, TestData, TestTxn, load_testing_data
from ..traces.synth import synth_trace

#: band -> (source, sizing).
#: "synth": (lo, hi) op-count range per doc.
#: "trace": (slot_budget, window_ins_cap) — the doc's total slot need
#: (init + window inserts) stays <= slot_budget, and the edit window is
#: additionally capped at window_ins_cap inserted chars (None = only the
#: budget caps it) so huge-class docs don't dominate drain time.
BANDS: dict[str, tuple[str, object]] = {
    "synth-small": ("synth", (24, 160)),
    "synth-medium": ("synth", (320, 900)),
    "synth-large": ("synth", (1400, 3400)),
    "trace-small": ("trace", (240, None)),
    "trace-medium": ("trace", (1000, None)),
    "trace-large": ("trace", (3900, None)),
    "trace-xl": ("trace", (8000, 1600)),
    "trace-huge": ("trace", (49000, 1200)),
}

#: band -> producer delivery burst (coalesced range ops pushed per
#: scheduler round) under ``delivery="banded"``.  Small interactive docs
#: trickle; big trace replays arrive in heavy bursts — the shape that
#: stresses a bounded admission queue.
DELIVERY_BURST: dict[str, int] = {
    "synth-small": 64, "synth-medium": 96, "synth-large": 128,
    "trace-small": 96, "trace-medium": 128, "trace-large": 192,
    "trace-xl": 256, "trace-huge": 256,
}

#: mix name -> {band: weight}.  "mixed" is the headline multi-tenant
#: blend; "synth"/"traces" isolate the two stream sources.
MIXES: dict[str, dict[str, float]] = {
    "mixed": {
        "synth-small": 0.36, "synth-medium": 0.12, "synth-large": 0.05,
        "trace-small": 0.20, "trace-medium": 0.12, "trace-large": 0.07,
        "trace-xl": 0.05, "trace-huge": 0.03,
    },
    "synth": {
        "synth-small": 0.60, "synth-medium": 0.28, "synth-large": 0.12,
    },
    "traces": {
        "trace-small": 0.35, "trace-medium": 0.25, "trace-large": 0.20,
        "trace-xl": 0.12, "trace-huge": 0.08,
    },
}


@dataclass
class Session:
    """One simulated tenant: a doc id, its edit stream, and when it
    joins the fleet (in scheduler rounds)."""

    doc_id: int
    band: str
    source: str  # "synth" or a real trace name
    trace: TestData
    arrival: int = 0
    burst: int | None = None  # producer delivery rate (ops/round)


# ---- multi-writer splitting (serve/replicate/) -----------------------------


def split_turns(n_ops: int, writers: int,
                turn_ops: int) -> list[tuple[int, int, int]]:
    """Partition a doc's op stream ``[0, n_ops)`` into contiguous
    **turn blocks** of up to ``turn_ops`` coalesced range ops, block
    ``j`` owned by writer ``j % writers`` — the round-robin authorship
    rotation the replication subsystem uses to turn one workload stream
    into W concurrent writers.  Returns ``[(lo, hi, writer), ...]`` in
    **sequence order**: block ``j`` covers ops ``[lo, hi)`` and the
    blocks concatenate back to exactly the original stream, so the
    group's arbitration order (ascending block sequence) reproduces the
    sequential oracle interleaving byte-for-byte.

    Deterministic and purely arithmetic: the same (n_ops, writers,
    turn_ops) always yields the same split — which is what makes a
    crashed replicated fleet recoverable from the workload alone."""
    if writers < 1:
        raise ValueError(f"writers must be >= 1, got {writers}")
    if turn_ops < 1:
        raise ValueError(f"turn_ops must be >= 1, got {turn_ops}")
    blocks: list[tuple[int, int, int]] = []
    lo = 0
    seq = 0
    while lo < n_ops:
        hi = min(lo + turn_ops, n_ops)
        blocks.append((lo, hi, seq % writers))
        lo = hi
        seq += 1
    return blocks


def replicate_sessions(
    sessions: "list[Session]", writers: int,
) -> "list[Session]":
    """Expand every logical session into ``writers`` replica sessions —
    one pool document per replica, dense doc ids ``logical * W + w``
    (writer ``w``'s replica of logical doc ``logical``).  Replicas
    share the SAME trace object, so ``prepare_streams``'s per-trace
    cache tensorizes each stream once and the replicas differ only in
    cursor/delivery state; they also share the logical session's
    arrival round (a writer group joins the fleet together).  The
    producer ``burst`` is dropped — delivery pacing belongs to the
    broadcast bus in replicated mode, not the banded producer model."""
    if writers < 1:
        raise ValueError(f"writers must be >= 1, got {writers}")
    out: list[Session] = []
    for s in sessions:
        for w in range(writers):
            out.append(Session(
                doc_id=s.doc_id * writers + w,
                band=s.band, source=s.source, trace=s.trace,
                arrival=s.arrival, burst=None,
            ))
    return out


@functools.lru_cache(maxsize=8)
def _full_trace(name: str) -> TestData:
    return load_testing_data(name)


@functools.lru_cache(maxsize=64)
def trace_prefix(name: str, slot_budget: int,
                 window_cap: int | None = None) -> TestData:
    """A real-trace session document: fold leading patches into
    ``start_content`` until the next patch fits the budget, then take
    the longest following patch window whose slot need (start chars +
    window inserts) stays within ``slot_budget`` (and, if given, whose
    window inserts stay within ``window_cap``).  ``end_content`` is left
    empty — the oracle defines truth for partial replays (same
    convention as traces/synth.py).  Raises if the trace cannot fit the
    budget at any fold point."""
    full = _full_trace(name)
    patches = list(full.iter_patches())
    doc = OracleDocument.from_str(full.start_content)
    fold = 0
    while fold <= len(patches):
        n_init = len(doc)
        if n_init <= slot_budget and fold < len(patches):
            need = n_init
            window = []
            win_ins = 0
            for p in patches[fold:]:
                need += len(p.ins)
                win_ins += len(p.ins)
                if need > slot_budget or (
                    window_cap is not None and win_ins > window_cap
                ):
                    break
                window.append(p)
            if window:
                return TestData(doc.content(), "", [TestTxn("", window)])
        if fold == len(patches):
            break
        p = patches[fold]
        doc.replace(p.pos, p.pos + p.del_count, p.ins)
        fold += 1
    raise ValueError(
        f"{name}: no patch window fits slot budget {slot_budget}"
    )


@functools.lru_cache(maxsize=64)
def _fitting_traces(slot_budget: int, window_cap: int | None) -> tuple:
    """Real traces that can provide a window for this budget.  Folding
    is bounded by how far the opening pastes reach; every budget >= 240
    admits at least automerge-paper (pure keystrokes from empty)."""
    fits = []
    for name in TRACES:
        try:
            trace_prefix(name, slot_budget, window_cap)
        except ValueError:
            continue
        fits.append(name)
    if not fits:
        raise ValueError(f"no trace fits slot budget {slot_budget}")
    return tuple(fits)


#: Skew exponent for ``arrival_dist="zipf"``: arrivals land at
#: ``span * u**ZIPF_EXP`` (u uniform), so a dense HEAD of sessions
#: joins in the first rounds — the live working set tier residency
#: serves from hot/warm — while a long TAIL trickles in across the
#: whole span and stays cold until it actually arrives.  The shape the
#: CRDT-deployment surveys report for real multi-tenant fleets.
ZIPF_EXP = 3.0


@dataclass(frozen=True)
class FleetSpec:
    """The fleet as ARITHMETIC, not objects: everything `build_fleet`
    would materialize for doc ``i`` is derivable from this spec in O(1)
    — band and arrival from three small per-doc arrays drawn up front
    (the only O(fleet) state, a few bytes per doc), the synth stream
    from a per-doc generator seeded ``(seed, doc_id)``.  The eager path
    is :meth:`session` mapped over the full range; the streaming path
    calls it per doc on first admission, so session/trace/stream cost
    scales with the ACTIVE set.

    Frozen + read-only arrays: a spec crosses into the prefetch worker
    inside construct-request builders, so nothing here may be mutable
    (graftlint G014's shared-state rule, honored by construction)."""

    n_docs: int
    seed: int
    horizon: int
    delivery: str | None
    #: sorted band names; ``band_of`` indexes into this
    names: tuple[str, ...]
    #: band -> (source, sizing) table (BANDS or a test override)
    table: dict
    band_of: np.ndarray  # int16 band index per doc
    arrivals: np.ndarray  # int32 arrival round per doc
    #: exclusive running count of trace-band docs before each doc — the
    #: lazy equivalent of the eager path's global round-robin counter
    trace_ord: np.ndarray  # int32

    @staticmethod
    def build(
        n_docs: int,
        mix: str | dict[str, float] = "mixed",
        seed: int = 0,
        arrival_span: int = 8,
        bands: dict | None = None,
        delivery: str | None = None,
        horizon: int = 1,
        arrival_dist: str = "uniform",
    ) -> "FleetSpec":
        """Draw the per-fleet vectors (band assignment, arrivals) in the
        exact order the eager builder always drew them — same seed, same
        bands and arrival rounds, byte-for-byte."""
        weights = MIXES[mix] if isinstance(mix, str) else dict(mix)
        table = BANDS if bands is None else bands
        names = sorted(weights)
        w = np.asarray([weights[b] for b in names], float)
        if not np.all(w >= 0) or w.sum() <= 0:
            raise ValueError(f"bad mix weights {weights}")
        w = w / w.sum()
        if arrival_dist not in ("uniform", "zipf"):
            raise ValueError(
                f"unknown arrival_dist {arrival_dist!r} "
                "(expected 'uniform' or 'zipf')"
            )
        rng = np.random.default_rng(seed)
        band_of = rng.choice(len(names), size=n_docs, p=w)
        if arrival_span <= 1:
            arrivals = np.zeros(n_docs, int)
        elif arrival_dist == "zipf":
            arrivals = np.floor(
                arrival_span * rng.random(n_docs) ** ZIPF_EXP
            ).astype(int)
        else:
            arrivals = rng.integers(0, arrival_span, size=n_docs)
        is_trace = np.asarray(
            [1 if table[b][0] == "trace" else 0 for b in names],
            np.int32,
        )[band_of] if n_docs else np.zeros(0, np.int32)
        trace_ord = np.zeros(n_docs, np.int64)
        if n_docs:
            np.cumsum(is_trace[:-1], out=trace_ord[1:])
        band_of = np.ascontiguousarray(band_of, np.int16)
        arrivals = np.ascontiguousarray(arrivals, np.int32)
        trace_ord = np.ascontiguousarray(trace_ord, np.int32)
        for a in (band_of, arrivals, trace_ord):
            a.flags.writeable = False
        return FleetSpec(
            n_docs=int(n_docs), seed=int(seed),
            horizon=max(1, int(horizon)), delivery=delivery,
            names=tuple(names), table=dict(table),
            band_of=band_of, arrivals=arrivals, trace_ord=trace_ord,
        )

    def band(self, doc_id: int) -> str:
        return self.names[int(self.band_of[doc_id])]

    def session(self, doc_id: int) -> Session:
        """Materialize ONE session in O(1) fleet-independent work: the
        per-doc draws come from ``default_rng((seed, doc_id))`` — the
        SeedSequence tuple derivation — so any doc's stream is
        reproducible without touching any other doc's.  Identical
        between the eager and streaming paths by construction (the
        eager builder is this method mapped over the full range)."""
        if not 0 <= doc_id < self.n_docs:
            raise IndexError(f"doc {doc_id} outside fleet {self.n_docs}")
        band = self.band(doc_id)
        source, sizing = self.table[band]
        if source == "synth":
            lo, hi = sizing
            r = np.random.default_rng((self.seed, doc_id))
            n_ops = int(r.integers(lo, hi + 1)) * self.horizon
            trace = synth_trace(
                seed=int(r.integers(1 << 31)), n_ops=n_ops
            )
            src = "synth"
        else:
            budget, cap = sizing
            fits = _fitting_traces(int(budget), cap)
            src = fits[int(self.trace_ord[doc_id]) % len(fits)]
            trace = trace_prefix(src, int(budget), cap)
        burst = (
            DELIVERY_BURST.get(band) if self.delivery == "banded" else None
        )
        return Session(
            doc_id=doc_id, band=band, source=src, trace=trace,
            arrival=int(self.arrivals[doc_id]), burst=burst,
        )

    def sessions(self) -> list[Session]:
        """The whole fleet, eagerly (the legacy shape)."""
        return [self.session(i) for i in range(self.n_docs)]

    def shard_range(self, shard: int, n_shards: int) -> tuple[int, int]:
        """The ``[lo, hi)`` doc-id range shard ``shard`` of ``n_shards``
        owns — the balanced contiguous split (sizes differ by at most
        one).  The spec is pure ``(seed, doc_id)`` arithmetic, so a
        shard materializes its range with NO reference to any other
        shard's docs: this is the whole of the ROADMAP million-doc
        item (d), the mesh split of the streaming path."""
        if not 0 <= shard < n_shards:
            raise ValueError(
                f"shard {shard} outside [0, {n_shards})"
            )
        return (
            shard * self.n_docs // n_shards,
            (shard + 1) * self.n_docs // n_shards,
        )

    def shard_doc_ids(self, shard: int, n_shards: int) -> range:
        """:meth:`shard_range` as an iterable of doc ids."""
        lo, hi = self.shard_range(shard, n_shards)
        return range(lo, hi)


def build_fleet(
    n_docs: int,
    mix: str | dict[str, float] = "mixed",
    seed: int = 0,
    arrival_span: int = 8,
    bands: dict | None = None,
    delivery: str | None = None,
    horizon: int = 1,
    arrival_dist: str = "uniform",
) -> list[Session]:
    """N sessions drawn from the mix's band weights, with arrival rounds
    staggered over ``arrival_span`` rounds — ``arrival_dist="uniform"``
    spreads them evenly (the historical default),
    ``arrival_dist="zipf"`` draws them skewed (:data:`ZIPF_EXP`): a
    dense head of early joiners forms a REAL hot set while the tail
    trickles in, the access skew that makes a warm tier pay.  ``mix``
    is a name from MIXES or an explicit {band: weight} table; ``bands``
    overrides the band sizing table (tests use tiny bands).
    ``delivery="banded"`` attaches each band's :data:`DELIVERY_BURST`
    producer rate to its sessions (consumed by the scheduler's bounded
    admission queue); the default delivers each stream whole.

    ``horizon`` is the **longhaul** multiplier (``serve/longhaul``
    family): synthetic sessions carry ``horizon``-times the band's op
    count — the days-of-edits-scale stream a long-lived document
    accumulates, generated as one valid edit history (synth streams are
    position-consistent at any length, so the oracle stays exact).
    Real-trace windows are bounded by their trace, so they keep the
    band's sizing and supply the capacity-class spread; the synthetic
    streams supply the history depth that stresses WAL growth, delta
    chains, and the recovery-time objective.

    Implemented as :class:`FleetSpec` mapped over the full doc range,
    so the eager fleet and the streaming path's lazily-admitted one are
    byte-identical by construction — same bands, arrivals, trace
    assignments, and per-doc synth streams for the same seed."""
    return FleetSpec.build(
        n_docs, mix=mix, seed=seed, arrival_span=arrival_span,
        bands=bands, delivery=delivery, horizon=horizon,
        arrival_dist=arrival_dist,
    ).sessions()
