"""Churn-heavy lifecycle leak check: the drain-end zero-leak gate.

The headline capability of graftlint v5's runtime twin: drive a small
real fleet through EVERY lifecycle protocol the static model declares
— keyed doc live↔cold residency churn on a hot budget a fraction of
the fleet, a live reshard (the `row` coordinator machine), a real
ingest front with connection churn and a resumed session (the
`session` machine over the wire), the warm tier's prefetch thread
(`thread` ownership), and a second journal-less streaming drain with
drained-doc record eviction (the `stream` machine plus O(active-set)
pool records) — all under ``lint/lifecycle_sanitizer.py`` armed, then
require **zero unreleased acquisitions** at drain end:
``assert_all_released()`` after an explicit teardown (evict residents,
GC the drained records, stop the prefetcher, stop the front) plus zero
unattributed transitions.

This is the dynamic proof of the G022–G025 static model: if any state
write bypassed its transition function (G022), any acquire lost its
release on some churn path (G023), or any id-keyed table survived a
generation bump (G024), this drain would either raise a typed
lifecycle error at the offending callsite or leave a named leak in the
gate.  The per-machine edge counts are asserted NONZERO so the harness
can never silently cover nothing — and the counters it emits are
exactly the ``lifecycle`` artifact block G025 cross-checks.

Runs as a tier-1 test (tests/test_lifecheck.py) and as the
``serve-longhaul`` smoke's lifecycle leg::

    JAX_PLATFORMS=cpu python -m crdt_benches_tpu.serve.lifecheck
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import sys
import tempfile

from ..lint import lifecycle_sanitizer as lifecycle
from .ingest.front import IngestFront, encode_frame
from .journal import OpJournal
from .pool import DocPool
from .reshard import ReshardCoordinator, parse_reshard_spec
from .scheduler import FleetScheduler, LazyStreams, prepare_streams
from .workload import FleetSpec, build_fleet

#: Tiny but protocol-complete AND churn-heavy: two capacity classes on
#: a 4-row hot budget against an 8-doc fleet (every round evicts and
#: restores — the keyed doc machine walks live->cold->live
#: constantly), a 3-doc warm tier with the prefetch worker armed, a
#: ``drain:1`` reshard beginning on the first round, sub-KiB WAL
#: segments, and a live ingest front churned mid-session.  ``small``
#: shrinks the streams for the tier-1 test, keeping every protocol.
_BANDS = {
    "synth-small": ("synth", (10, 60)),
    "synth-medium": ("synth", (150, 360)),
}
_MIX = {"synth-small": 0.7, "synth-medium": 0.3}
_SMALL_BANDS = {"synth-small": ("synth", (8, 36))}
_SMALL_MIX = {"synth-small": 1.0}
_CLASSES = (256, 1024)
_SLOTS = (2, 2)  # % _SHARDS == 0: one row of each class per shard
_SHARDS = 2
_RESHARD = "drain:1@0,of=2,batch=2"
_WARM = 3
_DOCS = 8
_SEED = 23
_BATCH = 16
_CHARS = 64
_MACRO_K = 2


def _sessions(small: bool = False):
    if small:
        return build_fleet(5, mix=_SMALL_MIX, seed=_SEED,
                           arrival_span=1, bands=_SMALL_BANDS)
    return build_fleet(_DOCS, mix=_MIX, seed=_SEED, arrival_span=2,
                       bands=_BANDS)


# ---------------------------------------------------------------------------
# a minimal wire client (the session machine needs REAL connections)
# ---------------------------------------------------------------------------


def _speak(port: int, frames: list[dict]) -> list[dict]:
    """One connection: send each frame, collect each reply.  Stops
    early when the server ends the conversation (churn/err/closed
    peer) — the remaining frames belong to a connection that no longer
    exists, exactly the client contract."""
    replies: list[dict] = []
    with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
        f = s.makefile("rwb")
        for frame in frames:
            f.write(encode_frame(frame))
            f.flush()
            line = f.readline()
            if not line:
                break
            reply = json.loads(line)
            replies.append(reply)
            if reply.get("t") in ("churn", "err"):
                break
    return replies


def _exercise_front(front: IngestFront, doc_id: int) -> None:
    """Three real sessions against a started front: a clean
    open/ops/close, a session dropped by connection churn mid-stream,
    and its resume — covering every edge of the session machine
    (new->open twice, open->dropped, open->closed)."""
    port = front.port
    assert port is not None
    r = _speak(port, [
        {"t": "hello", "session": "lc-a", "doc": doc_id,
         "tenant": "default"},
        {"t": "ops", "seq": 0, "start": 0, "count": 4, "round": 0},
        {"t": "bye"},
    ])
    assert [x.get("t") for x in r] == ["ack", "ack", "ack"], r
    front.drain()
    # churned session: the fault fires between the hello and the next
    # frame; the handler replies `churn` and surfaces the drop
    with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
        f = s.makefile("rwb")
        f.write(encode_frame({"t": "hello", "session": "lc-b",
                              "doc": doc_id, "tenant": "default"}))
        f.flush()
        assert json.loads(f.readline()).get("t") == "ack"
        front.drain()
        front.churn()
        f.write(encode_frame(
            {"t": "ops", "seq": 0, "start": 0, "count": 2, "round": 0}))
        f.flush()
        assert json.loads(f.readline()).get("t") == "churn"
    front.drain()
    r = _speak(port, [
        {"t": "hello", "session": "lc-b", "doc": doc_id,
         "tenant": "default", "resume": True},
        {"t": "bye"},
    ])
    assert [x.get("t") for x in r] == ["ack", "ack"], r
    front.drain()
    assert front.sessions_opened == 3, front.status_fields()
    assert front.sessions_resumed == 1, front.status_fields()
    assert front.sessions_closed == 2, front.status_fields()
    assert front.churn_drops == 1, front.status_fields()


# ---------------------------------------------------------------------------
# the two drains
# ---------------------------------------------------------------------------


def _teardown_pool(pool: DocPool) -> int:
    """Release every residual acquisition a completed drain leaves in
    the pool: spool out still-resident docs (their rows are live
    `rows` acquisitions), reclaim every record through the two-phase
    GC, and stop the prefetch thread.  Returns the records reclaimed."""
    for doc_id, rec in sorted(pool.docs.items()):
        if rec.cls is not None:
            pool.evict(doc_id)
    reclaimed = pool.gc_drained_docs(sorted(pool.docs))
    pool.close()
    return reclaimed


def _journaled_churn_drain(base: str, small: bool = False) -> dict:
    """Drain 1: journaled residency churn + reshard + warm/prefetch +
    a live churned ingest front.  Returns the scheduler's stats
    needed by the report."""
    sp = os.path.join(base, "spool")
    jd = os.path.join(base, "journal")
    sessions = _sessions(small)
    pool = DocPool(classes=_CLASSES, slots=_SLOTS, spool_dir=sp,
                   shards=_SHARDS, warm_docs=_WARM)
    front = IngestFront({s.doc_id for s in sessions})
    journal = OpJournal(jd, segment_bytes=128 if small else 192)
    try:
        streams = prepare_streams(sessions, pool, batch=_BATCH,
                                  batch_chars=_CHARS)
        reshard = ReshardCoordinator(
            pool, journal, parse_reshard_spec(_RESHARD)
        )
        sched = FleetScheduler(
            pool, streams, batch=_BATCH, macro_k=_MACRO_K,
            batch_chars=_CHARS, journal=journal, reshard=reshard,
            snapshot_every=2, snapshot_full_every=2,
        )
        front.start()
        _exercise_front(front, sessions[0].doc_id)
        sched.run()
        assert reshard.state == "done", reshard.state
        churn = pool.evictions + pool.restores + pool.warm_evictions
        assert churn > 0, "no residency churn — the doc machine is idle"
        return {"evictions": pool.evictions, "restores": pool.restores,
                "rounds": sched.round}
    finally:
        journal.close()
        _teardown_pool(pool)
        front.stop()


def _record_evict_drain(base: str, small: bool = False) -> dict:
    """Drain 2: journal-less streaming construction with drained-doc
    record eviction — the O(active-set) footprint path (ROADMAP
    million-doc item (b)).  Pool records at drain end are bounded by
    the active set (hot rows + warm budget + one unflushed GC batch),
    NOT the fleet."""
    sp = os.path.join(base, "spool")
    n = 12 if small else 3 * _DOCS
    spec = FleetSpec.build(
        n, mix=_SMALL_MIX if small else _MIX, seed=_SEED,
        arrival_span=4, bands=_SMALL_BANDS if small else _BANDS,
    )
    pool = DocPool(classes=_CLASSES, slots=_SLOTS, spool_dir=sp,
                   warm_docs=_WARM)
    try:
        streams = LazyStreams(spec, pool, batch=_BATCH,
                              batch_chars=_CHARS)
        sched = FleetScheduler(
            pool, streams, batch=_BATCH, macro_k=_MACRO_K,
            batch_chars=_CHARS, drained_gc=True,
        )
        sched.run()
        bound = sum(_SLOTS) + _WARM + 32  # active set + one GC batch
        records = len(pool.docs)
        assert records <= bound, (
            f"pool records {records} exceed the active-set bound "
            f"{bound} on a {n}-doc fleet — record eviction regressed"
        )
        assert sched.spool_gc_docs > 0, "record eviction never fired"
        return {"fleet": n, "records_at_end": records,
                "gc_docs": sched.spool_gc_docs,
                "released_streams": streams.released}
    finally:
        _teardown_pool(pool)


#: machines/resources the two drains must exercise — a zero count for
#: any of these means the harness silently stopped covering it
_REQUIRED_MACHINES = ("doc", "row", "session", "stream")
_REQUIRED_RESOURCES = ("rows", "thread", "socket")


def run_lifecheck(workdir: str | None = None, log=lambda s: None,
                  small: bool = False) -> dict:
    """The full check.  Returns a report dict::

        {"machines": {m: edges}, "resources": {...}, "leaked": 0,
         "unattributed": [], "churn": {...}, "record_evict": {...}}

    Both drains run ARMED in one counter window: every typed lifecycle
    error (illegal edge, wrong-state departure, double release,
    use-after-release, negative gauge) raises at its callsite, and the
    teardown gate requires zero live acquisitions + zero unattributed
    transitions at the end of each drain.
    """
    own = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="crdt_lifecheck_")
    lifecycle.reset_counters()
    lifecycle.arm()
    try:
        base = os.path.join(workdir, "churn")
        os.makedirs(base)
        churn = _journaled_churn_drain(base, small)
        lifecycle.assert_all_released()
        log(f"lifecheck: churn drain clean — {churn['evictions']} "
            f"evictions, {churn['restores']} restores, zero leaks")
        base = os.path.join(workdir, "evict")
        os.makedirs(base)
        evict = _record_evict_drain(base, small)
        lifecycle.assert_all_released()
        log(f"lifecheck: record-evict drain clean — "
            f"{evict['gc_docs']} records reclaimed, "
            f"{evict['records_at_end']} left on a {evict['fleet']}-doc "
            "fleet, zero leaks")
        c = lifecycle.counters()
        for name in _REQUIRED_MACHINES:
            if not c["machines"].get(name):
                raise AssertionError(
                    f"machine `{name}` recorded zero transitions — "
                    "the harness no longer covers it"
                )
        for res in _REQUIRED_RESOURCES:
            t = c["resources"].get(res) or {}
            if not t.get("acquire") or t.get("acquire") != t.get("release"):
                raise AssertionError(
                    f"resource `{res}` acquire/release imbalance in a "
                    f"leak-free run: {t}"
                )
        if c["unattributed"]:
            raise AssertionError(
                f"unattributed transitions: {c['unattributed']}"
            )
        return {
            "machines": c["machines"],
            "resources": c["resources"],
            "leaked": lifecycle.live_count(),
            "unattributed": c["unattributed"],
            "churn": churn,
            "record_evict": evict,
        }
    finally:
        if not lifecycle.sanitizing():
            lifecycle.disarm()
        if own:
            shutil.rmtree(workdir, ignore_errors=True)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    small = "--small" in argv
    if [a for a in argv if a != "--small"]:
        print("usage: python -m crdt_benches_tpu.serve.lifecheck "
              "[--small]", file=sys.stderr)
        return 2
    report = run_lifecheck(log=lambda s: print(s, flush=True),
                           small=small)
    edges = sum(n for t in report["machines"].values()
                for n in t.values())
    acqs = sum(t.get("acquire", 0)
               for t in report["resources"].values())
    ok = report["leaked"] == 0 and not report["unattributed"]
    print(
        f"lifecheck: {'OK' if ok else 'FAILED'} — {edges} transitions "
        f"across {len(report['machines'])} machines, {acqs} "
        f"acquisitions all released, zero unattributed"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
