"""Adversarial dtype-edge check: the value-range harness.

The headline capability of graftlint v6's runtime twin: drive the serve
stack with workloads BUILT to live at the edges the G026-G029 value-
range model guards — documents grown to exactly their capacity class,
ops at every position extreme (prepend at 0, append at len, the last
char, the full-doc wipe), deletes that empty a document and inserts
that refill it, rounds whose staged lanes are entirely PAD, and slot-id
spaces driven to the top of the narrow uint16 ladder and across the
uint16 boundary on the wide ladder — every drain replayed through BOTH
serve kernels (fused and scan) with ``lint/range_sanitizer.py`` armed,
and every final document byte-verified against the pure-Python oracle
AND against the other kernel.

These are exactly the inputs where XLA's clamp-don't-fault gather
semantics and a narrow-lane wrap would corrupt silently: an
off-by-one in any clamp region the static rules annotate (the
``mask=`` pairs), a missed widen before uint16 arithmetic, or a PAD
payload escaping its mask shows up here as a typed sanitizer error at
the staging callsite or as a byte mismatch against the oracle — never
as a green run.

The second leg is a seeded differential fuzz of the jit-boundary
contract registry (``lint/boundary.py``): for EVERY ``@boundary``
entry it synthesizes a conforming call at the contract's dtype edges
(arrays filled with ``iinfo(dtype).min``/``max``) and asserts the
checker accepts it, then perturbs one contract field at a time — an
edge-dtype swap on every typed lane, a rank bump on every shaped
argument, an inconsistent symbolic-dim binding, an aliased donated
buffer — and asserts every single perturbation is rejected.  The
differential (conforming accepted, each one-field edge perturbation
refused) is what pins the contract checker itself against drift.

Runs as a tier-1 test (tests/test_edgecheck.py, ``--small``) and as
the ``serve-longhaul`` smoke's ranges leg::

    JAX_PLATFORMS=cpu python -m crdt_benches_tpu.serve.edgecheck
"""

from __future__ import annotations

import importlib
import os
import shutil
import sys
import tempfile

import numpy as np

from ..lint import range_sanitizer as ranges
from ..lint.boundary import REGISTRY, BoundaryError, _check_call
from ..oracle.text_oracle import replay_trace
from ..traces.loader import TestData, TestPatch, TestTxn
from ..traces.synth import synth_trace
from .pool import DocPool
from .scheduler import FleetScheduler, prepare_streams
from .workload import Session

_SEED = 7
_BATCH = 16
_MACRO_K = 2

#: The narrow ladder's largest legal class (the biggest multiple of the
#: 128-lane tile that still fits the uint16 id space) and the wide
#: ladder's smallest: the two pools that bracket the uint16 boundary.
#: 65408 = 511 * 128 <= 65535 < 65664 = 513 * 128.
_NARROW_MAX_CLASS = 65408
_WIDE_MIN_CLASS = 65664

#: checks/masks a green run must have dispatched — a zero count means
#: the harness silently stopped covering a declared range contract
_REQUIRED_CHECKS = ("pool.macro-pos", "pool.macro-ids", "pool.write-row")
_REQUIRED_MASKS = ("count-le-clamp", "fused-gap-gather")


# ---------------------------------------------------------------------------
# adversarial trace construction
# ---------------------------------------------------------------------------


class _Script:
    """A legal-by-construction patch script: tracks the visible length
    so every emitted patch is in-contract (positions within the doc at
    op time), which keeps the harness adversarial about VALUES at the
    edges, never about malformed streams."""

    def __init__(self, start: str = ""):
        self.start = start
        self.len = len(start)
        self.patches: list[TestPatch] = []

    def ins(self, pos: int, text: str) -> None:
        assert 0 <= pos <= self.len, (pos, self.len)
        self.patches.append(TestPatch(pos, 0, text))
        self.len += len(text)

    def delete(self, pos: int, n: int) -> None:
        assert 0 <= pos and pos + n <= self.len, (pos, n, self.len)
        self.patches.append(TestPatch(pos, n, ""))
        self.len -= n

    def wipe(self) -> None:
        """The full-doc delete: [0, len) exactly."""
        if self.len:
            self.delete(0, self.len)

    def trace(self) -> TestData:
        td = TestData(self.start, "", [TestTxn("", list(self.patches))])
        return TestData(self.start, replay_trace(td), td.txns)


def _chars(n: int, salt: int) -> str:
    return "".join(chr(97 + (salt + j) % 26) for j in range(n))


def _position_extremes() -> TestData:
    """Every op-position edge on one small doc: insert at 0, at len,
    at len-1, delete of the first and last char, the exact full wipe,
    the refill of an emptied doc, down to a single-char doc."""
    s = _Script("ab")
    s.ins(0, "L")  # prepend into a non-empty doc
    s.ins(s.len, "R")  # append at exactly len
    s.ins(s.len - 1, "m")  # one before the end
    s.ins(s.len // 2, "c")  # interior, for contrast
    s.delete(0, 1)  # first char
    s.delete(s.len - 1, 1)  # last char
    s.wipe()  # delete [0, len) — the doc is now empty
    s.ins(0, "xyz")  # insert into the emptied doc
    s.delete(1, 1)
    s.wipe()
    s.ins(0, "q")  # end as a single-char doc
    return s.trace()


def _empty_churn(cycles: int) -> TestData:
    """Grow-from-empty / wipe-to-empty churn, ending EMPTY — the
    zero-length decode edge, reached repeatedly, from an empty
    start_content (n_init = 0)."""
    s = _Script("")
    for i in range(cycles):
        s.ins(0, _chars(i % 3 + 1, i))
        s.ins(s.len, _chars(1, i + 7))
        s.wipe()
    return s.trace()


def _all_pad_stream() -> TestData:
    """The zero-op trace: no patches at all.  Its tensorized stream is
    pure padding — the literal all-PAD round — and its final content
    is its (empty) start content."""
    return TestData("", "", [])


def _capacity_exact(cap: int, run: int = 48, full_end: bool = False,
                    init: str = "ab") -> TestData:
    """Drive a doc's capacity need (n_init + total inserted chars) to
    EXACTLY ``cap`` — the class-boundary doc.  Growth runs rotate
    through the position extremes (0 / len / mid).  ``full_end`` keeps
    every char, so the final visible length equals the class capacity
    (a completely full row); otherwise the doc is deleted down to a
    handful of chars, leaving capacity at the edge but the row mostly
    dead — both shapes cross the same clamp regions differently."""
    s = _Script(init)
    budget = cap - len(init)
    assert budget >= 0, (cap, init)
    i = 0
    while budget:
        n = min(run, budget)
        pos = 0 if i % 3 == 0 else (s.len if i % 3 == 1 else s.len // 2)
        s.ins(pos, _chars(n, i))
        budget -= n
        i += 1
    if not full_end and s.len > 7:
        s.delete(0, s.len - 7)
    return s.trace()


def _id_pressure(cap: int, run: int) -> TestData:
    """Pure append growth to capacity ``cap``: slot ids climb
    monotonically to ``cap - 1`` (the top of the pool's id space) and
    insert positions climb with them — on the 65408-class narrow
    ladder this staffs the uint16 lanes with their largest legal
    values; on the 65664-class wide ladder the same script carries ids
    ACROSS the uint16 boundary in int32 lanes.  Ends deleted down to a
    stub so the decode compare stays cheap while the ids stay maximal."""
    s = _Script("")
    while s.len < cap:
        n = min(run, cap - s.len)
        s.ins(s.len, _chars(n, s.len))
    if s.len > 9:
        s.delete(0, s.len - 9)
    return s.trace()


def _small_fleet() -> list[Session]:
    """The small-ladder fleet: every structural edge on a (256, 512)
    class pair, plus seeded random mass.  Arrivals are staggered so
    early rounds stage PAD rows for not-yet-arrived docs and late
    rounds stage PAD rows for drained ones."""
    traces = [
        _position_extremes(),
        _empty_churn(6),
        _all_pad_stream(),
        _capacity_exact(256, full_end=True),  # visible len == class cap
        _capacity_exact(255),  # one under the boundary
        _capacity_exact(257),  # one over: lands in the 512 class
        _id_pressure(256, run=48),  # ids to the top of the 256 space
        synth_trace(101, 220),
        synth_trace(102, 60, base="hello world"),
    ]
    arrivals = [0, 2, 1, 0, 1, 0, 3, 0, 2]
    return [
        Session(doc_id=i, band="edge", source="edge", trace=t, arrival=a)
        for i, (t, a) in enumerate(zip(traces, arrivals))
    ]


def _ladder_fleet(cap: int) -> list[Session]:
    """The uint16-bracket fleets: one doc at the big class's exact
    capacity with maximal ids, one small-class edge doc, one random."""
    return [
        Session(doc_id=0, band="edge", source="edge",
                trace=_id_pressure(cap, run=896), arrival=0),
        Session(doc_id=1, band="edge", source="edge",
                trace=_position_extremes(), arrival=1),
        Session(doc_id=2, band="edge", source="edge",
                trace=synth_trace(103, 120), arrival=0),
    ]


# ---------------------------------------------------------------------------
# the armed differential drains
# ---------------------------------------------------------------------------


def _drain(workdir: str, tag: str, sessions, classes, slots, kernel: str,
           batch_chars: int) -> tuple[dict[int, str], int]:
    """One armed drain: build the pool on ``kernel``, run the fleet,
    byte-verify every doc against the oracle, return the decodes (for
    the cross-kernel compare) and the round count."""
    sp = os.path.join(workdir, f"{tag}-{kernel}")
    pool = DocPool(classes=classes, slots=slots, spool_dir=sp,
                   serve_kernel=kernel)
    try:
        streams = prepare_streams(sessions, pool, batch=_BATCH,
                                  batch_chars=batch_chars)
        sched = FleetScheduler(pool, streams, batch=_BATCH,
                               macro_k=_MACRO_K, batch_chars=batch_chars)
        sched.run()
        out: dict[int, str] = {}
        for s in sessions:
            if not any(True for _ in s.trace.iter_patches()):
                # the zero-op stream: registered, but the scheduler
                # never stages a round for it, so it is never admitted
                # — decode refusing is the contract, and the doc's
                # content is its (empty) start content
                try:
                    pool.decode(s.doc_id)
                except ValueError:
                    out[s.doc_id] = s.trace.start_content
                    continue
                raise AssertionError(
                    f"{tag}/{kernel}: zero-op doc {s.doc_id} was "
                    "admitted — a pure-PAD stream staged real rounds"
                )
            got = pool.decode(s.doc_id)
            want = replay_trace(s.trace)
            if got != want:
                i = next(
                    (k for k, (a, b) in enumerate(zip(got, want)) if a != b),
                    min(len(got), len(want)),
                )
                raise AssertionError(
                    f"{tag}/{kernel}: doc {s.doc_id} diverges from the "
                    f"oracle at char {i} (got len {len(got)}, want "
                    f"{len(want)}): {got[i:i + 12]!r} != {want[i:i + 12]!r}"
                )
            out[s.doc_id] = got
        return out, sched.round
    finally:
        pool.close()


def _run_ladder(workdir: str, log, tag: str, sessions, classes, slots,
                batch_chars: int) -> dict:
    """One fleet through BOTH kernels: each oracle-verified, then the
    two decode maps compared byte-for-byte (the kernel differential)."""
    fused, r_f = _drain(workdir, tag, sessions, classes, slots, "fused",
                        batch_chars)
    scan, r_s = _drain(workdir, tag, sessions, classes, slots, "scan",
                       batch_chars)
    if fused != scan:
        bad = sorted(d for d in fused if fused[d] != scan.get(d))
        raise AssertionError(
            f"{tag}: fused and scan kernels disagree on docs {bad}"
        )
    log(f"edgecheck: {tag} clean — {len(sessions)} docs x 2 kernels, "
        f"oracle-identical ({r_f}+{r_s} rounds)")
    return {"docs": len(sessions), "classes": list(classes),
            "rounds": {"fused": r_f, "scan": r_s}}


# ---------------------------------------------------------------------------
# the boundary-contract differential fuzz
# ---------------------------------------------------------------------------

#: modules whose import registers every @boundary contract (the same
#: list the lint CLI's --boundaries dump imports, plus the ops-level
#: entries imported transitively there but named here explicitly)
_BOUNDARY_MODULES = (
    "crdt_benches_tpu.ops.resolve",
    "crdt_benches_tpu.serve.pool",
    "crdt_benches_tpu.engine.replay",
    "crdt_benches_tpu.engine.replay_range",
    "crdt_benches_tpu.engine.merge",
    "crdt_benches_tpu.engine.merge_range",
    "crdt_benches_tpu.engine.merge_fleet",
    "crdt_benches_tpu.engine.downstream",
    "crdt_benches_tpu.engine.downstream_range",
)

#: the dtype-edge swap set: for every typed lane, each of these that
#: differs from the declared dtype must be rejected
_EDGE_DTYPES = ("int8", "uint16", "int32", "int64")


def _contract_args(c, rng) -> list:
    """A conforming argument list for contract ``c`` at its dtype
    edges: every typed/shaped slot is a real array of the declared
    dtype with symbolic dims bound to seeded sizes, filled with the
    dtype's ``iinfo`` extremes; unchecked slots (state pytrees) are a
    one-leaf list so the donation alias check has a buffer to track."""
    n = max(len(c.dtypes), len(c.shapes), max(c.donates, default=-1) + 1)
    env: dict[str, int] = {}
    args: list = []
    for i in range(n):
        dt = c.dtypes[i] if i < len(c.dtypes) else None
        spec = c.shapes[i] if i < len(c.shapes) else None
        if dt is None and spec is None:
            args.append([np.zeros(int(rng.integers(2, 5)), np.int32)])
            continue
        if spec is not None:
            shape = tuple(
                int(t) if t.isdigit()
                else env.setdefault(t, int(rng.integers(2, 6)))
                for t in spec.split()
            )
        else:
            shape = (int(rng.integers(2, 6)),)
        dtype = np.dtype(dt or "int32")
        info = np.iinfo(dtype)
        a = np.full(shape, info.max, dtype=dtype)
        a.reshape(-1)[::2] = info.min  # both edges on every lane
        args.append(a)
    return args


def _expect_reject(c, args, what: str) -> None:
    try:
        _check_call(c, tuple(args))
    except BoundaryError:
        return
    raise AssertionError(
        f"boundary fuzz: {c.name} ACCEPTED a {what} perturbation — "
        "the contract checker no longer rejects it"
    )


def _fuzz_contract(c, rng) -> dict:
    """Differential fuzz of one registry entry: the conforming
    edge-filled call must pass, then every one-field perturbation
    (edge-dtype swap, rank bump, inconsistent symbolic binding,
    aliased donation) must raise BoundaryError."""
    args = _contract_args(c, rng)
    _check_call(c, tuple(args))  # the conforming baseline
    rejects = 0
    for i, want in enumerate(c.dtypes):
        if want is None:
            continue
        for ed in _EDGE_DTYPES:
            if ed == want:
                continue
            bad = list(args)
            bad[i] = args[i].astype(ed)
            _expect_reject(c, bad, f"arg{i} {want}->{ed} dtype")
            rejects += 1
    sym_seen: dict[str, int] = {}
    sym_pair = None  # (arg index, dim index) of a repeated symbol
    for i, spec in enumerate(c.shapes):
        if spec is None:
            continue
        bad = list(args)
        bad[i] = args[i][None]  # rank bump
        _expect_reject(c, bad, f"arg{i} rank")
        rejects += 1
        for d, tok in enumerate(spec.split()):
            if tok.isdigit():
                continue
            if tok in sym_seen and sym_pair is None and sym_seen[tok] != i:
                sym_pair = (i, d)
            sym_seen.setdefault(tok, i)
    if sym_pair is not None:
        i, d = sym_pair
        bad = list(args)
        shape = list(args[i].shape)
        shape[d] += 1  # contradicts the binding made by an earlier arg
        bad[i] = np.zeros(shape, args[i].dtype)
        _expect_reject(c, bad, "symbolic-dim binding")
        rejects += 1
    for i in c.donates:
        j = next(
            (k for k, a in enumerate(args)
             if k != i and isinstance(a, np.ndarray)),
            None,
        )
        if j is None:
            continue
        bad = list(args)
        bad[i] = [bad[j]]  # the donated pytree aliases arg j's buffer
        _expect_reject(c, bad, f"donated-arg{i} aliasing arg{j}")
        rejects += 1
    return {"rejects": rejects}


def _fuzz_boundaries(seed: int, log, rounds: int = 4) -> dict:
    """Seeded differential fuzz of EVERY @boundary registry entry at
    its contract's dtype edges (module docstring, second leg)."""
    for mod in _BOUNDARY_MODULES:
        importlib.import_module(mod)
    # registry keys are "module.qualname": fuzz the repo's contracts
    # only, not toy @boundary functions other suites may have
    # registered in-process (the registry is a global)
    ours = [n for n in sorted(REGISTRY)
            if n.startswith("crdt_benches_tpu.")]
    if not ours:
        raise AssertionError("boundary registry is empty after imports")
    per: dict[str, int] = {}
    total = 0
    for name in ours:
        c = REGISTRY[name]
        rng = np.random.default_rng((seed, hash(name) & 0xFFFF))
        n = 0
        for _ in range(rounds):
            n += _fuzz_contract(c, rng)["rejects"]
        if n == 0:
            raise AssertionError(
                f"boundary fuzz: {name} produced no rejectable "
                "perturbations — the contract declares nothing checkable"
            )
        per[name] = n
        total += n
    log(f"edgecheck: boundary fuzz clean — {len(per)} contracts, "
        f"{total} edge perturbations all rejected")
    return {"contracts": len(per), "rejected": total, "per_entry": per}


# ---------------------------------------------------------------------------
# the harness
# ---------------------------------------------------------------------------


def run_edgecheck(workdir: str | None = None, log=lambda s: None,
                  small: bool = False) -> dict:
    """The full check.  Returns a report dict::

        {"ladders": {tag: {...}}, "checks": {...}, "masks": {...},
         "boundary_fuzz": {...}}

    Every drain runs with the range sanitizer ARMED in one counter
    window: any staged index outside its declared bound, any narrow
    lane past its headroom, any PAD payload on a checked lane raises a
    typed error at the staging callsite; every final doc is oracle-
    and cross-kernel-verified; the required check/mask counters are
    asserted nonzero so the harness can never silently cover nothing.
    ``small`` keeps the structural edges and drops the two big-ladder
    fleets (the uint16 bracket) — the tier-1 shape.
    """
    own = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="crdt_edgecheck_")
    ranges.reset_counters()
    ranges.arm()
    try:
        ladders: dict[str, dict] = {}
        ladders["small-ladder"] = _run_ladder(
            workdir, log, "small-ladder", _small_fleet(),
            classes=(256, 512), slots=(2, 2), batch_chars=64,
        )
        if not small:
            # the uint16 bracket: the largest narrow ladder (ids to
            # the top of the uint16 space) and the smallest wide one
            # (ids across the uint16 boundary in int32 lanes)
            ladders["narrow-max"] = _run_ladder(
                workdir, log, "narrow-max", _ladder_fleet(_NARROW_MAX_CLASS),
                classes=(256, _NARROW_MAX_CLASS), slots=(2, 1),
                batch_chars=256,
            )
            ladders["wide-min"] = _run_ladder(
                workdir, log, "wide-min", _ladder_fleet(_WIDE_MIN_CLASS),
                classes=(256, _WIDE_MIN_CLASS), slots=(2, 1),
                batch_chars=256,
            )
        c = ranges.counters()
        for name in _REQUIRED_CHECKS:
            if not c["checks"].get(name):
                raise AssertionError(
                    f"check `{name}` recorded zero dispatches — the "
                    "harness no longer covers it"
                )
        for tag in _REQUIRED_MASKS:
            if not c["masks"].get(tag):
                raise AssertionError(
                    f"mask `{tag}` recorded zero dispatches — the "
                    "harness no longer covers it"
                )
        fuzz = _fuzz_boundaries(_SEED, log)
        return {
            "ladders": ladders,
            "checks": c["checks"],
            "masks": c["masks"],
            "boundary_fuzz": fuzz,
        }
    finally:
        if not ranges.sanitizing():
            ranges.disarm()
        if own:
            shutil.rmtree(workdir, ignore_errors=True)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    small = "--small" in argv
    if [a for a in argv if a != "--small"]:
        print("usage: python -m crdt_benches_tpu.serve.edgecheck "
              "[--small]", file=sys.stderr)
        return 2
    try:
        report = run_edgecheck(log=lambda s: print(s, flush=True),
                               small=small)
    except (AssertionError, ranges.RangeSanitizerError) as e:
        print(f"edgecheck: FAILED — {e}", file=sys.stderr)
        return 1
    docs = sum(t["docs"] for t in report["ladders"].values())
    checks = sum(report["checks"].values())
    print(
        f"edgecheck: OK — {docs} docs x 2 kernels across "
        f"{len(report['ladders'])} ladders oracle-identical, "
        f"{checks} armed range checks, "
        f"{report['boundary_fuzz']['rejected']} boundary edge "
        "perturbations rejected"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
