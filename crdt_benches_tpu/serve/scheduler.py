"""Admission + batching scheduler for the document fleet: macro-rounds.

Drains per-doc op queues into fixed-shape device batches.  Every
**macro-round**, each active capacity class gets one ``(K_eff, Rt, B)``
RANGE-op tensor — K_eff staged rounds of B ops for the doc in each of the
first Rt rows, idle lanes padded with ``kind == PAD`` no-ops — and the
pool applies it in ONE jitted ``lax.scan`` dispatch
(``pool.macro_step``).  Three coordinated mechanisms:

- **macro-rounds**: residency decisions are made once per K rounds, so a
  doc admitted for a macro-round receives up to ``K * B`` ops before the
  next placement decision — cutting the eviction/restore churn of the
  round-loop engine by ~K and replacing K dispatch+fence round-trips
  with one async dispatch;
- **async staged dispatch**: while macro-round ``m`` executes on device,
  the host plans and tensorizes macro-round ``m+1`` (selection,
  placement, and capacity arithmetic are host-only).  The only device
  syncs are the boundary **bucket pulls** when rows actually move
  (evict / promote / relocate) and the final drain fence;
- **RLE op coalescing + row compaction**: streams are run-length-coded
  range ops (``tensorize_ranges(coalesce=True)``) so one op slot carries
  a whole typing run or delete range (the semidirect-product composition
  of adjacent ops, PAPERS.md arXiv 2004.04303), and each macro-round the
  scheduled docs are compacted into the lowest row tier ``Rt`` (per mesh
  shard) so the device scan never streams idle rows.

Policy (deterministic, host-only — no device syncs on the decision path):

- **round-robin fairness**: active docs are served in FIFO order and
  rotate to the back after being scheduled, so a huge doc cannot starve
  the fleet;
- **class selection per macro-round**: a doc's capacity need after its
  next K slices is host-known (n_init + cumulative inserted chars), so
  promotion to a larger class happens *before* the macro-round that
  would overflow — the device never sees an over-capacity insert;
- **eviction**: when a selected doc's target bucket has no free row, the
  scheduler evicts a resident that is not scheduled this macro-round —
  finished docs first, then least-recently-scheduled — through the
  pool's checkpoint spool.  A selected set never exceeds the bucket's
  row count, so a victim always exists.
- **arrival**: each doc becomes active at its session's arrival round
  (the workload's arrival staggering), modeling sessions joining a live
  server rather than a cold batch job.
"""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ..traces.tensorize import (
    INSERT,
    PAD,
    split_insert_runs,
    tensorize_ranges,
)
from .pool import DocPool, _fresh_row_np
from ..utils.checkpoint import load_state


@dataclass
class DocStream:
    """One doc's pending op queue (host-side, read-only arrays + cursor).

    Ops are COALESCED RANGE ops: consecutive-position insert runs and
    same/backspace delete runs merged at stream build
    (``coalesce_patches``), then insert runs re-split to at most
    ``batch_chars`` chars (``split_insert_runs``) so any single op fits a
    slice's insert budget."""

    doc_id: int
    kind: np.ndarray  # int32[N] range ops (unpadded)
    pos: np.ndarray
    rlen: np.ndarray
    slot0: np.ndarray
    ins_cum: np.ndarray  # int32[N] inclusive cumulative INSERT chars
    unit_cum: np.ndarray  # int32[N] inclusive cumulative unit-op count
    n_patches: int
    arrival: int = 0
    cursor: int = 0

    @property
    def remaining(self) -> int:
        return len(self.kind) - self.cursor

    def ins_before(self, i: int) -> int:
        """Inserted chars in ops [0, i)."""
        return int(self.ins_cum[i - 1]) if i > 0 else 0

    def units_before(self, i: int) -> int:
        return int(self.unit_cum[i - 1]) if i > 0 else 0


def prepare_streams(sessions, pool: DocPool, batch: int = 64,
                    batch_chars: int = 256) -> dict[int, DocStream]:
    """Tensorize every session's trace as coalesced range ops, register
    the docs with the pool, and return the per-doc op queues.  Sessions
    sharing an identical trace object (the workload caches trace
    prefixes) share the tensorized arrays — the queues only differ in
    cursor state."""
    streams: dict[int, DocStream] = {}
    cache: dict[int, tuple] = {}  # id(trace) -> (arrays, rt)
    for s in sessions:
        hit = cache.get(id(s.trace))
        if hit is None:
            rt = tensorize_ranges(s.trace, batch=1, coalesce=True)
            n = rt.n_ops
            arrays = split_insert_runs(
                rt.kind[:n], rt.pos[:n], rt.rlen[:n], rt.slot0[:n],
                batch_chars,
            )
            ins_cum = np.cumsum(
                np.where(arrays[0] == INSERT, arrays[2], 0)
            ).astype(np.int32)
            unit_cum = np.cumsum(arrays[2]).astype(np.int32)
            hit = cache[id(s.trace)] = (arrays, ins_cum, unit_cum, rt)
        (kind, pos, rlen, slot0), ins_cum, unit_cum, rt = hit
        pool.register(
            s.doc_id, n_init=len(rt.init_chars),
            capacity_need=rt.capacity, chars=rt.chars,
        )
        streams[s.doc_id] = DocStream(
            doc_id=s.doc_id,
            kind=kind, pos=pos, rlen=rlen, slot0=slot0,
            ins_cum=ins_cum, unit_cum=unit_cum,
            n_patches=rt.n_patches,
            arrival=getattr(s, "arrival", 0),
        )
    return streams


@dataclass
class ServeStats:
    """One drain's telemetry (the serve family's report surface)."""

    round_latencies: list[float] = field(default_factory=list)
    compile_flags: list[bool] = field(default_factory=list)  # per round
    occupancy: list[float] = field(default_factory=list)  # per round
    queue_depth: list[int] = field(default_factory=list)  # per round
    rounds: int = 0  # macro-rounds dispatched
    slices: int = 0  # inner device rounds (sum of K_eff per class)
    ops: int = 0  # coalesced range ops applied
    unit_ops: int = 0  # unit-op equivalent (sum of run lengths)
    staged_cells: int = 0  # op slots staged across all macro tensors
    patches: int = 0
    evictions: int = 0
    restores: int = 0
    promotions: int = 0
    admissions: int = 0
    wall_time: float = 0.0

    @property
    def coalesce_ratio(self) -> float:
        """Unit ops represented per staged range op (>= 1; the RLE win)."""
        return self.unit_ops / self.ops if self.ops else 1.0

    @property
    def pad_fraction(self) -> float:
        """Fraction of staged op slots that were PAD — occupancy waste
        after row compaction (1 - real ops / staged cells)."""
        if not self.staged_cells:
            return 0.0
        return 1.0 - self.ops / self.staged_cells

    # NOTE: compile-time / steady-latency derivation lives in ONE place,
    # bench/harness.py steady_quantiles (compile_flags feed it).


@dataclass
class _Lane:
    stream: DocStream
    takes: list[int]  # range ops consumed per slice (len <= K)
    end: int  # cursor after the macro-round
    row: int = -1


@dataclass
class _Plan:
    base_round: int
    lanes: dict[int, list[_Lane]] = field(default_factory=dict)
    k_eff: dict[int, int] = field(default_factory=dict)
    rt: dict[int, int] = field(default_factory=dict)
    # data movement (executed at the sync boundary, planned host-side):
    pull_classes: set[int] = field(default_factory=set)
    evictions: list[tuple[int, int, int]] = field(default_factory=list)
    # target class -> [(doc_id, row, source)]; source is ('fresh',),
    # ('spool', path), or ('pull', src_cls, src_row)
    installs: dict[int, list[tuple[int, int, tuple]]] = field(
        default_factory=dict
    )
    waiting: int = 0


def _pow2ceil(x: int) -> int:
    return 1 << max(0, int(x - 1).bit_length())


class FleetScheduler:
    def __init__(self, pool: DocPool, streams: dict[int, DocStream],
                 batch: int = 64, macro_k: int = 1,
                 batch_chars: int = 256):
        self.pool = pool
        self.streams = streams
        self.batch = batch
        self.macro_k = max(1, macro_k)
        self.batch_chars = batch_chars
        self.nbits = max(1, int(batch_chars).bit_length())
        self.round = 0
        # FIFO of doc ids not yet arrived or with pending ops, in
        # arrival order (stable for determinism).
        self._rr = deque(sorted(
            streams, key=lambda d: (streams[d].arrival, d)
        ))
        self.stats = ServeStats(
            patches=sum(s.n_patches for s in streams.values())
        )

    # ---- planning (host-only; no device syncs) ----

    def _sim_takes(self, st: DocStream) -> tuple[list[int], int]:
        """Per-slice op counts for one doc's next macro-round: each slice
        takes up to ``batch`` range ops bounded by ``batch_chars``
        inserted chars (ops are pre-split, so at least one op always
        fits).  Returns (takes, end_cursor)."""
        takes: list[int] = []
        c = st.cursor
        N = len(st.kind)
        for _ in range(self.macro_k):
            if c >= N:
                break
            hi = min(c + self.batch, N)
            cap = st.ins_before(c) + self.batch_chars
            e = c + int(
                np.searchsorted(st.ins_cum[c:hi], cap, side="right")
            )
            e = max(e, c + 1)
            takes.append(e - c)
            c = e
        return takes, c

    def _select(self, plan: _Plan) -> None:
        """Pick this macro-round's lanes: {class: [_Lane]}, bounded by
        each bucket's row count, in round-robin order."""
        scheduled: list[int] = []
        deferred: list[int] = []
        while self._rr:
            doc_id = self._rr.popleft()
            st = self.streams[doc_id]
            if st.remaining == 0:
                continue  # drained: drop from the rotation for good
            if st.arrival > self.round:
                deferred.append(doc_id)
                continue
            takes, end = self._sim_takes(st)
            rec = self.pool.docs[doc_id]
            need = rec.n_init + st.ins_before(end)
            cls = self.pool.class_for(max(need, rec.length, 1))
            lanes = plan.lanes.setdefault(cls, [])
            if len(lanes) >= self.pool.buckets[cls].R:
                plan.waiting += 1
                deferred.append(doc_id)
                continue
            lanes.append(_Lane(stream=st, takes=takes, end=end))
            scheduled.append(doc_id)
        # rotation: scheduled docs go to the back; deferred keep order.
        self._rr.extend(deferred)
        self._rr.extend(scheduled)

    def _pick_victim(self, cls: int, selected: set[int],
                     selected_all: set[int]) -> int:
        """Eviction victim in ``cls``: finished docs first, then the
        least recently scheduled pending doc not selected this round.
        Docs scheduled in ANY class this round (e.g. a resident about to
        promote out of ``cls``) are spared when possible — evicting one
        would turn its direct promotion into a spool round-trip — but
        remain the liveness fallback: only this class's own selected set
        is guaranteed to leave a candidate."""
        candidates = [
            d for d, _row in self.pool.residents(cls) if d not in selected
        ]
        if not candidates:
            raise RuntimeError(
                f"bucket c{cls}: no eviction candidate "
                "(selected set exceeds bucket rows?)"
            )
        preferred = [d for d in candidates if d not in selected_all]
        return min(
            preferred or candidates,
            key=lambda d: (
                self.streams[d].remaining > 0,  # finished docs first
                self.pool.docs[d].last_sched,
                d,
            ),
        )

    def _place(self, plan: _Plan) -> None:
        """Residency bookkeeping for every selected lane (evictions,
        promotions, spool restores, fresh admits) and per-class row
        compaction.  Pure host state — the data moves happen later, at
        the boundary (:meth:`_execute_moves`)."""
        pool = self.pool
        selected_all = {
            l.stream.doc_id for lanes in plan.lanes.values() for l in lanes
        }
        for cls in pool.classes:
            lanes = plan.lanes.get(cls)
            if not lanes:
                continue
            b = pool.buckets[cls]
            selected = {l.stream.doc_id for l in lanes}
            pending: list[tuple[int, tuple]] = []  # (lane idx, source)
            for i, lane in enumerate(lanes):
                rec = pool.docs[lane.stream.doc_id]
                if rec.cls == cls:
                    lane.row = rec.row
                    continue
                if rec.cls is not None:  # promotion out of a smaller class
                    pending.append((i, ("pull", rec.cls, rec.row)))
                    plan.pull_classes.add(rec.cls)
                    b_old = pool.buckets[rec.cls]
                    b_old.rows[rec.row] = None
                    b_old.release_row(rec.row)
                    rec.cls = rec.row = None
                    pool.promotions += 1
                elif rec.spool is not None:
                    pending.append((i, ("spool", rec.spool)))
                    rec.spool = None
                    pool.restores += 1
                else:
                    pending.append((i, ("fresh",)))
                    pool.fresh_admits += 1
                self.stats.admissions += 1
            # make room: one victim per missing free row
            while b.n_free < len(pending):
                victim = self._pick_victim(cls, selected, selected_all)
                vrec = pool.docs[victim]
                plan.evictions.append((victim, cls, vrec.row))
                plan.pull_classes.add(cls)
                vrec.spool = pool._spool_path(victim)
                b.rows[vrec.row] = None
                b.release_row(vrec.row)
                vrec.cls = vrec.row = None
                pool.evictions += 1
            # ---- occupancy-aware compaction: choose the row tier ----
            # pow2 K depths bound the compile-shape count; the macro_k
            # clamp keeps a non-pow2 --serve-macro from dispatching
            # guaranteed-all-PAD tail slices.
            k_eff = min(
                _pow2ceil(max(len(l.takes) for l in lanes)), self.macro_k
            )
            resident_locals = [
                (lane, divmod(lane.row, b.Rg)) for lane in lanes
                if lane.row >= 0
            ]
            n_installs = len(pending)
            chosen_rt = b.R
            relocs: list[tuple[_Lane, int]] = []
            install_rows: list[int] = []
            for rt_total in pool.tiers(cls):
                rt = rt_total // b.n_sh
                fb = [
                    sorted(l for l in b.free_locals(s) if l < rt)
                    for s in range(b.n_sh)
                ]
                high = [[] for _ in range(b.n_sh)]
                for lane, (s, l) in resident_locals:
                    if l >= rt:
                        high[s].append(lane)
                if any(len(high[s]) > len(fb[s]) for s in range(b.n_sh)):
                    continue
                spare = sum(len(fb[s]) - len(high[s]) for s in range(b.n_sh))
                if spare < n_installs:
                    continue
                chosen_rt = rt_total
                # relocations: high scheduled rows -> lowest free locals
                # on the same shard; installs fill remaining low rows,
                # balanced across shards.
                remaining: list[list[int]] = []
                for s in range(b.n_sh):
                    take = fb[s][: len(high[s])]
                    for lane, dst_l in zip(high[s], take):
                        relocs.append((lane, s * b.Rg + dst_l))
                    remaining.append(fb[s][len(high[s]):])
                for _ in range(n_installs):
                    s = max(
                        range(b.n_sh),
                        key=lambda i: (len(remaining[i]), -i),
                    )
                    install_rows.append(s * b.Rg + remaining[s].pop(0))
                break
            plan.k_eff[cls] = k_eff
            plan.rt[cls] = chosen_rt
            if chosen_rt == b.R:
                install_rows = []  # no tier: plain lowest-row allocation
            inst = plan.installs.setdefault(cls, [])
            for j, (i, source) in enumerate(pending):
                lane = lanes[i]
                rec = pool.docs[lane.stream.doc_id]
                if install_rows:
                    row = install_rows[j]
                    b.take_row(row)
                else:
                    row = b.alloc_row()
                b.rows[row] = rec.doc_id
                rec.cls, rec.row = cls, row
                lane.row = row
                inst.append((rec.doc_id, row, source))
            for lane, dst in relocs:
                rec = pool.docs[lane.stream.doc_id]
                src = rec.row
                plan.pull_classes.add(cls)
                inst.append((rec.doc_id, dst, ("pull", cls, src)))
                b.take_row(dst)
                b.rows[dst] = rec.doc_id
                b.rows[src] = None
                b.release_row(src)
                rec.row = dst
                lane.row = dst

    def _plan(self) -> _Plan | None:
        """One macro-round's full host plan, or None when drained.
        Advances the round clock over arrival-wait gaps."""
        while True:
            plan = _Plan(base_round=self.round)
            self._select(plan)
            if plan.lanes:
                self._place(plan)
                return plan
            pending = [
                s.arrival for s in self.streams.values()
                if s.remaining and s.arrival > self.round
            ]
            if not pending:
                return None
            self.round = min(pending)  # idle: jump to the next arrival

    # ---- staging (host tensorize; overlaps device execution) ----

    def _stage(self, plan: _Plan) -> dict[int, tuple]:
        tensors: dict[int, tuple] = {}
        B = self.batch
        for cls, lanes in plan.lanes.items():
            K, Rt = plan.k_eff[cls], plan.rt[cls]
            b = self.pool.buckets[cls]
            rt = Rt // b.n_sh
            kind = np.full((K, Rt, B), PAD, np.int32)
            pos = np.zeros((K, Rt, B), np.int32)
            rlen = np.zeros((K, Rt, B), np.int32)
            slot0 = np.full((K, Rt, B), -1, np.int32)
            for lane in lanes:
                st = lane.stream
                s, l = divmod(lane.row, b.Rg)
                r = s * rt + l  # sliced row index
                c = st.cursor
                for k, take in enumerate(lane.takes):
                    kind[k, r, :take] = st.kind[c:c + take]
                    pos[k, r, :take] = st.pos[c:c + take]
                    rlen[k, r, :take] = st.rlen[c:c + take]
                    slot0[k, r, :take] = st.slot0[c:c + take]
                    c += take
            tensors[cls] = (kind, pos, rlen, slot0)
        return tensors

    # ---- boundary execution (the only device syncs) ----

    def _execute_moves(self, plan: _Plan) -> None:
        """Apply the plan's row movement: pull affected buckets once
        (syncing with any in-flight macro step), write eviction spools,
        compose installs on host, upload each touched bucket once."""
        pool = self.pool
        snaps = {
            cls: pool.pull_bucket(cls) for cls in sorted(plan.pull_classes)
        }
        for doc_id, cls, row in plan.evictions:
            doc, length, nvis = snaps[cls]
            pool.spool_save(
                doc_id, doc[row], int(length[row]), int(nvis[row])
            )
        for cls, items in plan.installs.items():
            if not items:
                continue
            if cls in snaps:
                doc_s, len_s, nvis_s = snaps[cls]
            else:
                doc_s, len_s, nvis_s = pool.pull_bucket(cls)
            # writable copies: sources always read the pre-compose
            # snapshot, so a row can be both vacated and refilled in one
            # boundary without ordering hazards.
            doc_w = np.array(doc_s)
            len_w = np.array(len_s)
            nvis_w = np.array(nvis_s)
            C = self.pool.buckets[cls].C
            for doc_id, row, source in items:
                rec = pool.docs[doc_id]
                if source[0] == "fresh":
                    doc_w[row] = _fresh_row_np(C, rec.n_init)
                    len_w[row] = nvis_w[row] = rec.n_init
                elif source[0] == "spool":
                    st = load_state(source[1])
                    os.unlink(source[1])  # rehydrated: bound the spool
                    L = int(st.length[0])
                    doc_w[row, :L] = st.doc[0, :L]
                    doc_w[row, L:] = 2
                    len_w[row] = L
                    nvis_w[row] = int(st.nvis[0])
                else:  # ("pull", src_cls, src_row)
                    _, src_cls, src_row = source
                    sdoc, slen, snvis = snaps[src_cls]
                    L = int(slen[src_row])
                    doc_w[row, :L] = sdoc[src_row, :L]
                    doc_w[row, L:] = 2
                    len_w[row] = L
                    nvis_w[row] = int(snvis[src_row])
            pool.upload_bucket(cls, doc_w, len_w, nvis_w)

    # ---- dispatch + mirrors ----

    def _dispatch(self, plan: _Plan, tensors: dict[int, tuple]) -> bool:
        compiled = False
        for cls, (kind, pos, rlen, slot0) in tensors.items():
            compiled |= self.pool.macro_step(
                cls, kind, pos, rlen, slot0, nbits=self.nbits
            )
            self.stats.slices += plan.k_eff[cls]
            self.stats.staged_cells += kind.size
        return compiled

    def _advance(self, plan: _Plan) -> None:
        """Host mirrors after dispatch: the staged ops WILL be applied,
        and length/cursor evolve deterministically, so no sync is needed
        to keep scheduling exact."""
        lanes_used = 0
        for cls, lanes in plan.lanes.items():
            for lane in lanes:
                st = lane.stream
                rec = self.pool.docs[st.doc_id]
                self.stats.ops += lane.end - st.cursor
                self.stats.unit_ops += (
                    st.units_before(lane.end) - st.units_before(st.cursor)
                )
                st.cursor = lane.end
                rec.length = rec.n_init + st.ins_before(lane.end)
                rec.last_sched = plan.base_round
                lanes_used += 1
        total_lanes = sum(b.R for b in self.pool.buckets.values())
        self.stats.occupancy.append(lanes_used / total_lanes)
        self.stats.queue_depth.append(plan.waiting)
        self.round = plan.base_round + max(plan.k_eff.values())

    # ---- driver ----

    def run_round(self) -> bool:
        """One macro-round (plan -> stage -> boundary moves -> one async
        dispatch per class).  Returns False when no work remains."""
        t0 = time.perf_counter()
        plan = self._plan()
        if plan is None:
            return False
        tensors = self._stage(plan)
        self._execute_moves(plan)
        compiled = self._dispatch(plan, tensors)
        self._advance(plan)
        self.stats.round_latencies.append(time.perf_counter() - t0)
        self.stats.compile_flags.append(compiled)
        return True

    def run(self, max_rounds: int | None = None) -> ServeStats:
        """Drain every queue (or stop after ``max_rounds`` macro-rounds).
        Synchronization discipline: each run_round syncs only at its
        boundary moves; the device drains behind the host planner and is
        fenced once here at the end."""
        t0 = time.perf_counter()
        n = 0
        while self.run_round():
            n += 1
            if max_rounds is not None and n >= max_rounds:
                break
        tail0 = time.perf_counter()
        self.pool.block()  # final fence: the last macro-round's drain
        if self.stats.round_latencies:
            self.stats.round_latencies[-1] += time.perf_counter() - tail0
        self.stats.wall_time += time.perf_counter() - t0
        self.stats.rounds = len(self.stats.round_latencies)
        self.stats.evictions = self.pool.evictions
        self.stats.restores = self.pool.restores
        self.stats.promotions = self.pool.promotions
        return self.stats

    @property
    def done(self) -> bool:
        return all(s.remaining == 0 for s in self.streams.values())
