"""Admission + batching scheduler for the document fleet: macro-rounds.

Drains per-doc op queues into fixed-shape device batches.  Every
**macro-round**, each active capacity class gets one ``(K_eff, Rt, B)``
RANGE-op tensor — K_eff staged rounds of B ops for the doc in each of the
first Rt rows, idle lanes padded with ``kind == PAD`` no-ops — and the
pool applies it in ONE jitted ``lax.scan`` dispatch
(``pool.macro_step``).  Three coordinated mechanisms:

- **macro-rounds**: residency decisions are made once per K rounds, so a
  doc admitted for a macro-round receives up to ``K * B`` ops before the
  next placement decision — cutting the eviction/restore churn of the
  round-loop engine by ~K and replacing K dispatch+fence round-trips
  with one async dispatch;
- **async staged dispatch**: while macro-round ``m`` executes on device,
  the host plans and tensorizes macro-round ``m+1`` (selection,
  placement, and capacity arithmetic are host-only).  The only device
  syncs are the boundary **bucket pulls** when rows actually move
  (evict / promote / relocate) and the final drain fence;
- **RLE op coalescing + row compaction**: streams are run-length-coded
  range ops (``tensorize_ranges(coalesce=True)``) so one op slot carries
  a whole typing run or delete range (the semidirect-product composition
  of adjacent ops, PAPERS.md arXiv 2004.04303), and each macro-round the
  scheduled docs are compacted into the lowest row tier ``Rt`` (per mesh
  shard) so the device scan never streams idle rows.

Policy (deterministic, host-only — no device syncs on the decision path):

- **round-robin fairness**: active docs are served in FIFO order and
  rotate to the back after being scheduled, so a huge doc cannot starve
  the fleet;
- **class selection per macro-round**: a doc's capacity need after its
  next K slices is host-known (n_init + cumulative inserted chars), so
  promotion to a larger class happens *before* the macro-round that
  would overflow — the device never sees an over-capacity insert;
- **eviction**: when a selected doc's target bucket has no free row, the
  scheduler evicts a resident that is not scheduled this macro-round —
  finished docs first, then least-recently-scheduled — through the
  pool's checkpoint spool.  A selected set never exceeds the bucket's
  row count, so a victim always exists.
- **arrival**: each doc becomes active at its session's arrival round
  (the workload's arrival staggering), modeling sessions joining a live
  server rather than a cold batch job.

Fault tolerance (serve/journal.py + serve/faults.py wire in here):

- **bounded queues + backpressure**: with ``queue_cap > 0`` a doc's
  pending window is capped; delivery past the cap is an explicit
  decision — **defer** (producer backpressure, nothing lost) or
  **shed** (tail-drop the session's remaining ops; the doc is marked
  lossy, excluded from byte-verify, and the loss is surfaced as
  ``shed_ops``).  Silent overflow cannot happen;
- **write-ahead journal**: each macro-round's lane set is journaled
  BEFORE dispatch; snapshot barriers every ``snapshot_every`` rounds
  bound the redo tail (crash recovery = ``journal.recover_fleet``);
- **in-run repair**: a spool that fails its CRC on restore is rebuilt
  from the last snapshot base + stream replay (``journal.rebuild_doc``)
  through the same scan path; a class whose device state is lost
  mid-macro-round is rebuilt the same way, one row per resident.  A doc
  whose rebuild ALSO fails is **quarantined** — its remaining ops shed,
  its row freed — and the fleet keeps serving;
- **graceful degradation**: after ``degrade_after`` faults inside
  ``degrade_window`` rounds, the scheduler falls back from macro-K to
  K=1 synchronous rounds for ``degrade_rounds`` rounds (fence per
  round), then restores K automatically;
- **idempotent admission**: the per-doc cursor is the delivery
  high-water mark — a duplicated or stale-reordered batch is clamped
  against it and dropped (``dup_ops_dropped``), never re-applied.
"""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial

import numpy as np

from ..lint import lifecycle_sanitizer as lifecycle
from ..lint.sanitizer import entries_total, fenced, hot_path
from ..obs.metrics import (
    DEPTH_BUCKETS,
    LATENCY_BUCKETS_S,
    OCCUPANCY_BUCKETS,
    Histogram,
    MetricsRegistry,
)
from ..obs.reqtrace import RequestTracker
from ..obs.trace import span
from ..traces.tensorize import (
    INSERT,
    PAD,
    split_insert_runs,
    tensorize_ranges,
)
from .pool import DocPool, _fresh_row_np
from ..utils.checkpoint import CorruptCheckpointError, load_state
from .journal import (
    SnapshotBases,
    _read_manifest,
    list_snapshots,
    probe_recovery,
    rebuild_doc,
    retained_floor,
    write_snapshot,
)


@dataclass
class DocStream:
    """One doc's pending op queue (host-side, read-only arrays + cursor).

    Ops are COALESCED RANGE ops: consecutive-position insert runs and
    same/backspace delete runs merged at stream build
    (``coalesce_patches``), then insert runs re-split to at most
    ``batch_chars`` chars (``split_insert_runs``) so any single op fits a
    slice's insert budget.

    Queue-bounding state: ``delivered`` (None = unbounded) is how far
    the producer has pushed ops into the bounded pending window;
    ``limit`` truncates the stream (quarantine / load-shed tail-drop)
    and ``lossy`` marks docs excluded from byte-verification."""

    doc_id: int
    kind: np.ndarray  # [N] range ops (unpadded), in the pool's packed
    pos: np.ndarray   # lane dtypes (ops/packing.py op_lane_dtypes)
    rlen: np.ndarray
    slot0: np.ndarray
    ins_cum: np.ndarray  # int32[N] inclusive cumulative INSERT chars
    unit_cum: np.ndarray  # int32[N] inclusive cumulative unit-op count
    n_patches: int
    arrival: int = 0
    cursor: int = 0
    delivered: int | None = None  # bounded-queue fill point (None = all)
    limit: int | None = None  # stream truncation (shed / quarantine)
    lossy: bool = False  # ops were shed: excluded from byte-verify
    burst: int | None = None  # producer delivery rate (ops/round)
    deferred_high: int = 0  # highest op index ever backpressured

    @property
    def n_total(self) -> int:
        """Stream length after any shed truncation."""
        n = len(self.kind)
        return n if self.limit is None else min(self.limit, n)

    @property
    def remaining(self) -> int:
        return self.n_total - self.cursor

    @property
    def n_sched(self) -> int:
        """Ops visible to the scheduler: everything up to the bounded
        queue's fill point (the whole stream when unbounded)."""
        if self.delivered is None:
            return self.n_total
        return min(self.n_total, self.delivered)

    def ins_before(self, i: int) -> int:
        """Inserted chars in ops [0, i)."""
        return int(self.ins_cum[i - 1]) if i > 0 else 0

    def units_before(self, i: int) -> int:
        return int(self.unit_cum[i - 1]) if i > 0 else 0

    def slice_end(self, c: int, batch: int, batch_chars: int,
                  n: int) -> int:
        """End cursor of ONE device slice starting at ``c`` (bounded by
        ``n``): up to ``batch`` range ops and ``batch_chars`` inserted
        chars.  Ops are pre-split, so at least one always fits.  THE
        slice-budget rule — the scheduler's staging (``_sim_takes``) and
        the recovery replayer (``journal.rebuild_doc``) must size slices
        identically, so both call here."""
        hi = min(c + batch, n)
        cap = self.ins_before(c) + batch_chars
        e = c + int(np.searchsorted(self.ins_cum[c:hi], cap, side="right"))
        return max(e, c + 1)

    def clamp_redelivery(self, start: int, end: int) -> int:
        """Admit a (re)delivered batch ``[start, end)``: ops below the
        applied cursor are duplicates (or stale reorders) and are
        dropped — the cursor is the idempotence high-water mark.
        Returns the dropped-op count; the live stream always continues
        from ``cursor``, so nothing is ever applied twice."""
        return max(0, min(end, self.cursor) - max(0, start))


def _tensorize_trace(trace, batch_chars: int, max_class: int) -> tuple:
    """One trace -> packed coalesced range-op arrays + cumsums + the
    raw tensorization (for init/capacity metadata).  Pure function of
    its arguments — it also runs on the prefetch worker thread for
    streaming construction, so it must touch no shared mutable state."""
    from ..ops.packing import pack_ops

    rt = tensorize_ranges(trace, batch=1, coalesce=True)
    n = rt.n_ops
    arrays = split_insert_runs(
        rt.kind[:n], rt.pos[:n], rt.rlen[:n], rt.slot0[:n],
        batch_chars,
    )
    kind_a, pos_a, rlen_a, slot_a = arrays
    # slot0 is only ever read for INSERT ops; the tensorizer's
    # -1 sentinel on deletes would (rightly) fail the unsigned
    # lane's range check, so normalize it away first
    slot_a = np.where(kind_a == INSERT, slot_a, 0)
    arrays = pack_ops(
        kind_a, pos_a, rlen_a, slot_a, max_class=max_class,
    )
    ins_cum = np.cumsum(
        np.where(arrays[0] == INSERT, arrays[2], 0)
    ).astype(np.int32)
    unit_cum = np.cumsum(arrays[2]).astype(np.int32)
    return arrays, ins_cum, unit_cum, rt


def build_stream_payload(spec, doc_id: int, batch_chars: int,
                         max_class: int) -> dict:
    """Materialize ONE doc's session + tensorized stream as a plain
    dict of arrays — the streaming-construction payload.

    PURE by contract: everything derives from the frozen ``FleetSpec``
    and scalars, so the prefetch worker can run it off the drain and
    hand the result back through the declared ``publish=prefetch``
    point (``Prefetcher.submit_construct``).  Array keys carry an
    ``_a`` suffix so they never collide with the payload envelope's
    own ``kind`` tag."""
    s = spec.session(doc_id)
    (kind, pos, rlen, slot0), ins_cum, unit_cum, rt = _tensorize_trace(
        s.trace, batch_chars, max_class
    )
    return {
        "kind_a": kind, "pos_a": pos, "rlen_a": rlen, "slot0_a": slot0,
        "ins_cum": ins_cum, "unit_cum": unit_cum,
        "n_patches": rt.n_patches, "n_init": len(rt.init_chars),
        "capacity": rt.capacity, "chars": rt.chars,
        "arrival": s.arrival, "burst": s.burst,
    }


def prepare_streams(sessions, pool: DocPool, batch: int = 64,
                    batch_chars: int = 256) -> dict[int, DocStream]:
    """Tensorize every session's trace as coalesced range ops, register
    the docs with the pool, and return the per-doc op queues.  Sessions
    sharing an identical trace object (the workload caches trace
    prefixes) share the tensorized arrays — the queues only differ in
    cursor state.

    Stream arrays are stored in the pool's packed lane dtypes
    (``ops/packing.py``): packing here — once per distinct trace, with
    range checking that raises rather than wraps — means staging copies
    narrow-to-narrow and a macro round uploads half the bytes."""
    streams: dict[int, DocStream] = {}
    # id(trace)-keyed, G024-shaped — made safe by PINNING the trace
    # object in the cache value: a pinned id can never be freed and
    # recycled for the cache's lifetime, and the identity check
    # re-verifies the pin on every hit (the lazy path's cache poisoning
    # incident, closed at the eager path too).
    cache: dict[int, tuple] = {}  # id(trace) -> (trace pin, (arrays, rt))
    for s in sessions:
        hit = cache.get(id(s.trace))
        if hit is None or hit[0] is not s.trace:
            hit = cache[id(s.trace)] = (s.trace, _tensorize_trace(
                s.trace, batch_chars, max(pool.classes)
            ))
        (kind, pos, rlen, slot0), ins_cum, unit_cum, rt = hit[1]
        pool.register(
            s.doc_id, n_init=len(rt.init_chars),
            capacity_need=rt.capacity, chars=rt.chars,
        )
        streams[s.doc_id] = DocStream(
            doc_id=s.doc_id,
            kind=kind, pos=pos, rlen=rlen, slot0=slot0,
            ins_cum=ins_cum, unit_cum=unit_cum,
            n_patches=rt.n_patches,
            arrival=getattr(s, "arrival", 0),
            burst=getattr(s, "burst", None),
        )
    return streams


#: shared zero-length arrays for released streams: a drained doc's
#: DocStream keeps its identity (victim selection, fault paths, repeat
#: drain notes all still index it) but drops its op arrays — O(1) per
#: released doc instead of the full stream.
_EMPTY_I32 = np.zeros(0, np.int32)


class LazyStreams:  # graftlint: state=stream states=genesis,live,released edges=genesis->live,live->released
    """Mapping-shaped view over a :class:`FleetSpec`: the op queues of
    a fleet, materialized per doc on first access — the streaming
    construction path.  Construction cost and host footprint scale
    with the ACTIVE set: nothing exists for a doc (no session, no
    trace, no tensorized arrays, no pool record — GENESIS residency)
    until the scheduler first touches it.

    Dict-compatible surface the scheduler uses: ``[]`` (materializes),
    ``get``, ``in``, ``len``, ``keys``; ``values()`` / ``items()``
    iterate the LIVE (materialized) population only — full-fleet
    aggregates have lazy-aware branches in the scheduler instead.

    Materialization has three entry points:

    - :meth:`__getitem__` — synchronous, on the hot thread (the
      fallback path, and the common one for cold starts);
    - :meth:`adopt` — a stream the prefetch worker built off-drain
      (:func:`build_stream_payload` via ``submit_construct``) arrives
      through the declared publish point and is installed here;
    - :meth:`release` — the reverse edge: a drained doc's arrays are
      swapped for shared empty ones, so a long drain's footprint
      tracks the active set, not the docs ever seen."""

    def __init__(self, spec, pool: DocPool, batch: int = 64,
                 batch_chars: int = 256):
        self.spec = spec
        self.pool = pool
        self.batch = batch
        self.batch_chars = batch_chars
        self.bounded = False  # queue_cap mode: delivered=cursor at birth
        self._live: dict[int, DocStream] = {}
        self._tcache: dict = {}  # (band, trace name) -> tensorized
        self.materialized = 0
        self.released = 0
        self.prefetch_built = 0  # streams adopted from the worker
        self.patches_total = 0  # n_patches over materialized docs
        pool.set_genesis_population(spec.n_docs)
        # the stream construction machine's legal graph, mirrored from
        # the class marker (G022/G025): a doc's op queue is built once
        # and released once — there is no resurrection edge, adopt()
        # and release() both guard on the live table
        lifecycle.declare_machine(
            "stream", ("genesis", "live", "released"),
            (("genesis", "live"), ("live", "released")),
        )

    # ---- mapping surface ----

    def __len__(self) -> int:
        return self.spec.n_docs

    def __contains__(self, doc_id) -> bool:
        return 0 <= int(doc_id) < self.spec.n_docs

    def keys(self):
        return range(self.spec.n_docs)

    def values(self):
        """LIVE streams only (materialized, incl. released stubs)."""
        return self._live.values()

    def items(self):
        return self._live.items()

    def get(self, doc_id, default=None):
        """Non-materializing probe: the live stream or ``default``."""
        if doc_id is None:
            return default
        return self._live.get(int(doc_id), default)

    def __getitem__(self, doc_id: int) -> DocStream:
        st = self._live.get(doc_id)
        if st is None:
            st = self._materialize(self.spec.session(doc_id))
        return st

    # ---- materialization edges ----

    @fenced
    def _install(self, st: DocStream, n_init: int, capacity: int,  # graftlint: fence=genesis  # graftlint: transition=stream:genesis->live
                 chars) -> DocStream:
        lifecycle.transition("stream", "genesis", "live",
                             key=st.doc_id)
        self.pool.register(
            st.doc_id, n_init=n_init, capacity_need=capacity,
            chars=chars,
        )
        if self.bounded and st.delivered is None:
            st.delivered = st.cursor
        self._live[st.doc_id] = st
        self.materialized += 1
        self.patches_total += st.n_patches
        return st

    @fenced
    def _materialize(self, s) -> DocStream:  # graftlint: fence=genesis
        # Trace-band docs share the lru-cached ``trace_prefix`` object,
        # so their tensorization is cached per (band, trace): a few
        # entries, never more.  Synth traces are unique per doc AND
        # transient — the eager path's id(trace) key would poison the
        # cache here the moment CPython recycles a freed trace's id —
        # so they are tensorized directly, never cached.
        if s.source == "synth":
            hit = _tensorize_trace(
                s.trace, self.batch_chars, max(self.pool.classes)
            )
        else:
            key = (s.band, s.source)
            hit = self._tcache.get(key)
            if hit is None:
                hit = self._tcache[key] = _tensorize_trace(
                    s.trace, self.batch_chars, max(self.pool.classes)
                )
        (kind, pos, rlen, slot0), ins_cum, unit_cum, rt = hit
        return self._install(
            DocStream(
                doc_id=s.doc_id,
                kind=kind, pos=pos, rlen=rlen, slot0=slot0,
                ins_cum=ins_cum, unit_cum=unit_cum,
                n_patches=rt.n_patches, arrival=s.arrival,
                burst=s.burst,
            ),
            n_init=len(rt.init_chars), capacity=rt.capacity,
            chars=rt.chars,
        )

    def builder(self, doc_id: int):
        """The pure construct callable handed to the prefetch worker
        (it crosses threads ON the request queue — no shared mutable
        attribute exists, G014 by construction).  Deliberately a
        ``partial``, not a closure: :func:`build_stream_payload` runs
        on the PREFETCH thread, so the hot-path walk must not see a
        call edge into it from here — deferring through ``partial``
        keeps the static model aligned with the runtime."""
        return partial(
            build_stream_payload, self.spec, int(doc_id),
            self.batch_chars, max(self.pool.classes),
        )

    def adopt(self, doc_id: int, payload: dict) -> bool:
        """Install a worker-built stream (harvested construct payload).
        False when superseded — the doc already materialized through
        the synchronous path while the construction flew."""
        if doc_id in self._live:
            return False
        self._install(
            DocStream(
                doc_id=doc_id,
                kind=payload["kind_a"], pos=payload["pos_a"],
                rlen=payload["rlen_a"], slot0=payload["slot0_a"],
                ins_cum=payload["ins_cum"],
                unit_cum=payload["unit_cum"],
                n_patches=payload["n_patches"],
                arrival=payload["arrival"], burst=payload["burst"],
            ),
            n_init=payload["n_init"], capacity=payload["capacity"],
            chars=payload["chars"],
        )
        self.prefetch_built += 1
        return True

    def release(self, doc_id: int) -> None:  # graftlint: transition=stream:live->released
        """Drop a drained doc's op arrays (keep the stream object: the
        victim picker and fault paths still index it).  Idempotent."""
        st = self._live.get(doc_id)
        if st is None or st.kind is _EMPTY_I32:
            return
        lifecycle.transition("stream", "live", "released", key=doc_id)
        st.kind = st.pos = st.rlen = st.slot0 = _EMPTY_I32
        st.ins_cum = st.unit_cum = _EMPTY_I32
        st.cursor = 0
        st.limit = None
        if st.delivered is not None:
            st.delivered = 0
        self.released += 1

    @property
    def all_done(self) -> bool:
        """Every doc materialized at least once AND drained."""
        return (
            self.materialized >= self.spec.n_docs
            and all(s.remaining == 0 for s in self._live.values())
        )


#: Cause tags for the per-doc admission-to-drain latency series: how the
#: doc's stream ENDED.  Fixed set, pre-registered — G012 forbids
#: interpolating tag names on the hot path.
DOC_CAUSE_TAGS = ("ok", "deferred", "shed", "quarantined")


@dataclass
class ServeStats:
    """One drain's telemetry (the serve family's report surface).

    Per-round series live in fixed-bucket ``obs/metrics.py`` histograms
    registered in :attr:`metrics` — a million-round drain holds
    O(buckets) telemetry, not three million-float Python lists (the
    pre-obs ``occupancy`` / ``queue_depth`` / ``round_latencies``
    growth bug).  :meth:`note_round` is THE compile/barrier
    classification point: histograms, spans, the profiler's
    steady-round window, and the artifact's compile/barrier accounting
    all key off its flags — one source of truth.
    """

    metrics: MetricsRegistry = field(default_factory=MetricsRegistry)
    # test-only: retain raw per-round lists so parity tests can compare
    # histogram quantiles against the exact-list quantiles they replaced
    keep_raw: bool = False
    raw_round_latencies: list[float] = field(default_factory=list)
    raw_compile_flags: list[bool] = field(default_factory=list)
    raw_barrier_flags: list[bool] = field(default_factory=list)
    rounds: int = 0  # macro-rounds dispatched
    compile_time: float = 0.0  # wall time of compile-flagged rounds
    compile_rounds: int = 0
    barrier_time: float = 0.0  # wall time of snapshot-barrier rounds
    barrier_rounds: int = 0
    slices: int = 0  # inner device rounds (sum of K_eff per class)
    ops: int = 0  # coalesced range ops applied
    unit_ops: int = 0  # unit-op equivalent (sum of run lengths)
    staged_cells: int = 0  # op slots staged across all macro tensors
    patches: int = 0
    evictions: int = 0
    restores: int = 0
    promotions: int = 0
    admissions: int = 0
    wall_time: float = 0.0
    # ---- fault tolerance / graceful degradation ----
    shed_ops: int = 0  # ops dropped by an explicit load-shed decision
    deferred_ops: int = 0  # ops backpressured at the bounded queue cap
    overflow_events: int = 0
    backpressure_rounds: int = 0
    dup_ops_dropped: int = 0  # duplicated/stale redeliveries clamped
    stall_rounds: int = 0
    quarantines: list[dict] = field(default_factory=list)
    recoveries: int = 0  # successful in-run repairs (spool / device loss)
    ops_replayed: int = 0  # redo span re-applied by repairs
    replay_dispatches: int = 0
    mttr_rounds: list[int] = field(default_factory=list)  # per recovery
    degraded_rounds: int = 0  # macro-rounds served in the K=1 fallback
    faults_seen: int = 0  # faults the engine observed (incl. organic)
    faults_injected: int = 0  # events the injector fired
    snapshots: int = 0
    snapshots_full: int = 0  # chain-rooting full barriers
    snapshots_delta: int = 0  # dirty-row delta barriers
    snapshot_time: float = 0.0

    def __post_init__(self):
        m = self.metrics
        self.lat_steady = m.histogram(
            "serve.round.latency.steady", LATENCY_BUCKETS_S
        )
        self.lat_skipped = m.histogram(
            "serve.round.latency.skipped", LATENCY_BUCKETS_S
        )
        self.occupancy = m.histogram(
            "serve.round.occupancy", OCCUPANCY_BUCKETS
        )
        self.queue_depth = m.histogram(
            "serve.round.queue_depth", DEPTH_BUCKETS
        )
        self.doc_latency = {
            tag: m.histogram(
                "serve.doc.drain_latency." + tag, LATENCY_BUCKETS_S
            )
            for tag in DOC_CAUSE_TAGS
        }

    def note_round(self, latency: float, compiled: bool,
                   barrier: bool) -> None:
        """Record one macro-round.  THE round-classification rule:
        compile-flagged rounds (cold-start skew) and snapshot-barrier
        rounds (forced syncs) are excluded from the steady latency
        histogram and accounted separately — every consumer (artifact
        quantiles, trace spans, the device profiler's capture window)
        keys off these same two flags."""
        self.rounds += 1
        if compiled:
            self.compile_time += latency
            self.compile_rounds += 1
            self.lat_skipped.observe(latency)
        elif barrier:
            self.barrier_time += latency
            self.barrier_rounds += 1
            self.lat_skipped.observe(latency)
        else:
            self.lat_steady.observe(latency)
        if self.keep_raw:
            self.raw_round_latencies.append(latency)
            self.raw_compile_flags.append(compiled)
            self.raw_barrier_flags.append(barrier)

    @property
    def steady_rounds(self) -> int:
        return self.lat_steady.count

    def latency_quantiles(self, ps=(0.5, 0.95, 0.99)) -> dict[str, float]:
        """Steady-round latency quantiles; falls back to ALL rounds
        when every round was compile/barrier-flagged (tiny drains) —
        the same fallback ``bench/harness.py steady_quantiles`` applies
        to raw lists."""
        if self.lat_steady.count:
            return self.lat_steady.quantiles(ps)
        if self.lat_skipped.count:
            return Histogram.merged(
                self.lat_steady, self.lat_skipped
            ).quantiles(ps)
        return {f"p{100 * p:g}": 0.0 for p in ps}

    @property
    def coalesce_ratio(self) -> float:
        """Unit ops represented per staged range op (>= 1; the RLE win)."""
        return self.unit_ops / self.ops if self.ops else 1.0

    @property
    def pad_fraction(self) -> float:
        """Fraction of staged op slots that were PAD — occupancy waste
        after row compaction (1 - real ops / staged cells)."""
        if not self.staged_cells:
            return 0.0
        return 1.0 - self.ops / self.staged_cells

    def note_doc_drained(self, tag: str, seconds: float) -> None:
        """One document finished (or was explicitly ended): record its
        admission-to-drain latency under its cause tag."""
        self.doc_latency[tag].observe(seconds)


@dataclass
class _Lane:
    stream: DocStream
    takes: list[int]  # range ops consumed per slice (len <= K)
    end: int  # cursor after the macro-round
    row: int = -1


@dataclass
class _Plan:
    base_round: int
    lanes: dict[int, list[_Lane]] = field(default_factory=dict)
    k_eff: dict[int, int] = field(default_factory=dict)
    rt: dict[int, int] = field(default_factory=dict)
    # data movement (executed at the sync boundary, planned host-side):
    pull_classes: set[int] = field(default_factory=set)
    evictions: list[tuple[int, int, int]] = field(default_factory=list)
    # warm-mode victims whose state still lives in their old bucket row
    # until the boundary: a LATER class selecting such a doc converts
    # the eviction into a same-round pull (see _place)
    limbo: dict[int, tuple[int, int]] = field(default_factory=dict)
    cancelled_evictions: set[int] = field(default_factory=set)
    # target class -> [(doc_id, row, source)]; source is ('fresh',),
    # ('spool', path), or ('pull', src_cls, src_row)
    installs: dict[int, list[tuple[int, int, tuple]]] = field(
        default_factory=dict
    )
    waiting: int = 0


def _pow2ceil(x: int) -> int:
    return 1 << max(0, int(x - 1).bit_length())


class FleetScheduler:
    def __init__(self, pool: DocPool, streams: dict[int, DocStream],
                 batch: int = 64, macro_k: int = 1,
                 batch_chars: int = 256,
                 queue_cap: int = 0, overflow_policy: str = "defer",
                 faults=None, journal=None,
                 snapshot_every: int = 0, snapshot_keep: int = 2,
                 snapshot_full_every: int = 4,
                 degrade_after: int = 3, degrade_window: int = 8,
                 degrade_rounds: int = 4,
                 start_round: int = 0, profiler=None, telemetry=None,
                 reqtrace=None, slo=None,
                 warm_start: bool = False,
                 reshard=None,
                 drained_gc: bool = False, gc_keep=None):
        if overflow_policy not in ("defer", "shed"):
            raise ValueError(f"unknown overflow policy {overflow_policy!r}")
        self.pool = pool
        self.streams = streams
        self.batch = batch
        self.macro_k = max(1, macro_k)
        self.batch_chars = batch_chars
        self.nbits = max(1, int(batch_chars).bit_length())
        if warm_start:
            # deployment-time compile of the fused path's shared
            # executables — cold-start spread the drain never pays
            pool.warm_fused(self.batch, self.nbits)
        self.round = start_round
        self.queue_cap = max(0, queue_cap)
        self.overflow_policy = overflow_policy
        self.faults = faults  # serve/faults.py FaultInjector (or None)
        self.journal = journal  # serve/journal.py OpJournal (or None)
        self.snapshot_every = snapshot_every
        self.snapshot_keep = snapshot_keep
        #: every Nth barrier is a chain-rooting FULL snapshot; the ones
        #: between persist only rows dirty since the previous barrier
        #: (<=1 = every barrier full, the pre-delta behavior)
        self.snapshot_full_every = max(0, snapshot_full_every)
        self._barrier_count = 0
        self._pending_gc_ev = None  # crash_compact fired, GC torn
        self.degrade_after = degrade_after
        self.degrade_window = degrade_window
        self.degrade_rounds = degrade_rounds
        self._bases = SnapshotBases(journal.dir if journal else None)
        self._fault_rounds: deque[int] = deque()
        self._degrade_left = 0  # K=1 fallback rounds still to serve
        self._planned_degraded = False  # THIS round planned under K=1
        self._k_round = self.macro_k  # per-plan frozen macro depth
        self._dead_lanes: set[int] = set()  # quarantined mid-round
        self._bp_round = False
        self._snapped = False
        self._n_rounds = 0
        # streaming construction (LazyStreams): the rotation is FED
        # from the arrival-sorted order array as rounds reach each
        # doc's arrival — nothing exists for a doc (no session, no
        # stream, no pool record) until the scheduler touches it, so
        # setup cost and footprint scale with the active set.
        self._lazy = isinstance(streams, LazyStreams)
        if self._lazy:
            streams.bounded = self.queue_cap > 0
            arr = streams.spec.arrivals.astype(np.int64)
            order = np.argsort(arr, kind="stable")
            self._order = order.astype(np.int64)
            self._order_arrivals = arr[order]
            self._order_ptr = 0
            # FIFO of ARRIVED doc ids with pending ops (fed lazily)
            self._rr: deque[int] = deque()
            self._arrivals_sorted = self._order_arrivals
            # total patches is only known once every doc materializes:
            # run() backfills it from the lazy view at drain end
            self.stats = ServeStats(patches=0)
        else:
            self._order = None
            self._order_arrivals = None
            self._order_ptr = 0
            # FIFO of doc ids not yet arrived or with pending ops, in
            # arrival order (stable for determinism).
            self._rr = deque(sorted(
                streams, key=lambda d: (streams[d].arrival, d)
            ))
            # static arrival schedule + ended-doc set: the O(1) inputs
            # the _select early exit uses to count the unscanned
            # tail's TRUE waiting docs (arrived and not drained)
            # without touching it
            self._arrivals_sorted = np.sort(np.fromiter(
                (st.arrival for st in streams.values()), dtype=np.int64,
                count=len(streams),
            ))
            if self.queue_cap > 0:
                for st in streams.values():
                    if st.delivered is None:
                        st.delivered = st.cursor
            self.stats = ServeStats(
                patches=sum(s.n_patches for s in streams.values())
            )
        self._ended: set[int] = set()
        #: elastic reconfiguration (serve/reshard.py ReshardCoordinator,
        #: or None): ticked once per round after placement, finalized at
        #: drain end before the fault sweep
        self.reshard = reshard
        # drained-doc footprint GC (two-phase spool reclamation): only
        # journal-less drains may reclaim — recovery replays snapshot
        # chains whose members live in the spool dir
        if drained_gc and journal is not None:
            raise ValueError(
                "drained-doc GC requires a journal-less drain "
                "(recovery re-adopts spool members)"
            )
        self.drained_gc = drained_gc
        self._gc_keep = set(gc_keep or ())
        self._gc_queue: list[int] = []
        self.spool_gc_docs = 0  # records+members reclaimed so far
        self.profiler = profiler  # obs/profiler.py DeviceProfiler (or None)
        self._pending_round: tuple[float, bool, bool] | None = None
        # request lifecycle (obs/reqtrace.py): disarmed, the tracker is
        # exactly the old per-doc admission-timestamp table; armed
        # (--serve-reqtrace / --serve-slo) every admission opens a full
        # request context with segment timings + publish-point hops.
        self.reqtrace = reqtrace if reqtrace is not None \
            else RequestTracker()
        self.slo = slo  # obs/slo.py SloTracker (or None)
        # one registry per drain: pool / journal / fault counters attach
        # to it so the artifact's metrics block carries the whole run
        reg = self.stats.metrics
        pool.bind_metrics(reg)
        if journal is not None:
            journal.bind_metrics(reg)
        if faults is not None:
            faults.bind_metrics(reg)
        if slo is not None:
            slo.bind(reg)  # burn-rate gauges pre-registered (G013)
        if reshard is not None:
            reshard.bind_metrics(reg)  # serve.reshard.* (G013)
        self.reqtrace.bind(self.stats)
        self._m_faults_seen = reg.counter("serve.faults.seen")
        # durability gauges (pre-registered off the hot path, G013):
        # delta-chain depth of the newest barrier and the round of the
        # last WAL compaction pass — with the journal's own gauges
        # (segment count, bytes since snapshot) these are the live
        # bounded-footprint view on /metrics + /status.json
        self._g_chain_depth = reg.gauge("serve.durability.chain_depth")
        self._g_last_compact = reg.gauge(
            "serve.durability.last_compaction_round"
        )
        # continuous telemetry (obs/timeseries.py ServeTelemetry, or
        # None): per-round time-series windows, per-shard series, the
        # status endpoint and the soak anomaly detectors all hang off
        # this one bundle — bound here so every series lives in THIS
        # drain's registry.
        self.telemetry = telemetry
        # ---- predictive prefetch (tiered pool only): hot-thread-owned
        # accounting; the worker thread sees only the queues.  The
        # inflight table maps doc -> (submit round, seq) so entries
        # whose results never arrive (the worker's bounded publish
        # dropped them during a wedged round) are reaped BY SEQ instead
        # of pinning the submission budget forever — and a payload that
        # outlives its reaping is dropped at harvest without a second
        # inflight decrement ----
        self._prefetch_inflight: dict[int, tuple[int, int]] = {}
        #: cold docs rehydrated ahead of admission per round: the next
        #: macro-round's worth of admissions is the natural horizon
        self._prefetch_lookahead = max(
            32, sum(b.R for b in pool.buckets.values())
        )
        self.prefetch_wasted = 0  # harvested but stale/superseded
        self.prefetch_missed = 0  # planned but dropped (chaos kind)
        self.limbo_pulls = 0  # same-round victim→promotion conversions
        self._last_occ = 0.0
        self._last_queue = 0
        n_sh = pool.n_sh
        self._sh_lanes = [0] * n_sh
        self._sh_ops = [0] * n_sh
        self._sh_units = [0] * n_sh
        if telemetry is not None:
            telemetry.bind(pool, reg, reqtrace=self.reqtrace)

    # ---- degradation (automatic macro-K -> K=1 fallback) ----

    @property
    def effective_k(self) -> int:
        """Macro depth for the NEXT planned round: 1 while degraded."""
        return 1 if self._degrade_left > 0 else self.macro_k

    def _note_fault(self) -> None:
        """Track fault density; repeated faults inside the window trip
        (or extend) the K=1 synchronous fallback for ``degrade_rounds``
        dispatched rounds, starting with the next planned round."""
        self.stats.faults_seen += 1
        self._m_faults_seen.inc()
        self._fault_rounds.append(self.round)
        while (self._fault_rounds
               and self._fault_rounds[0] < self.round - self.degrade_window):
            self._fault_rounds.popleft()
        if (self.macro_k > 1 and self.degrade_after > 0
                and len(self._fault_rounds) >= self.degrade_after
                and self._degrade_left < self.degrade_rounds):
            self._degrade_left = self.degrade_rounds
            if self.journal:
                self.journal.event(
                    "degrade", r=self.round, rounds=self.degrade_rounds
                )

    # ---- bounded-queue delivery (backpressure is explicit) ----

    def _push_delivery(self, st: DocStream, want: int) -> int:
        """THE bounded-queue admission rule: clamp a producer push at
        ``queue_cap`` pending ops, counting each refused op ONCE (the
        ``deferred_high`` high-water mark) as ``deferred_ops``.  Both
        the per-round delivery and the overflow-burst fault go through
        here — one copy of the invariant.  Returns the deferred
        excess."""
        lim = st.cursor + self.queue_cap
        excess = max(0, want - lim)
        if excess:
            first_new = max(lim, st.deferred_high)
            newly = max(0, want - first_new)
            if newly:
                self.stats.deferred_ops += newly
                st.deferred_high = max(st.deferred_high, want)
            self._bp_round = True
        st.delivered = max(st.delivered, min(want, lim))
        return excess

    def _deliver(self, st: DocStream) -> None:
        """Advance the producer's delivery point into the bounded
        pending window.  Delivery past ``queue_cap`` pending ops is
        refused — the producer holds the excess (counted as
        ``deferred_ops`` the first time each op is pushed back)."""
        if st.delivered is None:
            return
        n = st.n_total
        want = n if st.burst is None else min(
            n, max(st.delivered, st.cursor) + st.burst
        )
        self._push_delivery(st, want)

    # ---- planning (host-only; no device syncs) ----

    def _sim_takes(self, st: DocStream) -> tuple[list[int], int]:
        """Per-slice op counts for one doc's next macro-round: each slice
        takes up to ``batch`` range ops bounded by ``batch_chars``
        inserted chars (ops are pre-split, so at least one op always
        fits).  Returns (takes, end_cursor)."""
        takes: list[int] = []
        c = st.cursor
        N = st.n_sched
        for _ in range(self._k_round):
            if c >= N:
                break
            e = st.slice_end(c, self.batch, self.batch_chars, N)
            takes.append(e - c)
            c = e
        return takes, c

    def _note_doc_drained(self, st: DocStream, tag: str | None = None
                          ) -> None:
        """One doc's stream is finished (drained, shed empty, or
        quarantined): close its request context and record the
        admission-to-drain latency under its cause tag.  The close pops
        the context, so each EPISODE is observed exactly once — and a
        doc re-admitted after a close (quarantine-rebuild, the ingest
        refill paths to come) opens a FRESH request context instead of
        being double-counted under its old one (the PR 6 ``_admit_t``
        doc-keyed scheme's bug, pinned by tests)."""
        self._ended.add(st.doc_id)
        if tag is None:
            if st.lossy:
                tag = "shed"
            elif st.deferred_high > 0:
                tag = "deferred"
            else:
                tag = "ok"
        dt = self.reqtrace.close_request(
            st.doc_id, tag, round_no=self.round
        )
        if self._lazy and self.journal is None:
            # streaming construction: a drained doc's op arrays are
            # dead weight (nothing replays them without a journal) —
            # drop them so footprint tracks the ACTIVE set
            self.streams.release(st.doc_id)
        if self.drained_gc and st.doc_id not in self._gc_keep:
            # past its last arrival window: the pool record and any
            # spool/shadow member are reclaimable (batched two-phase
            # GC at the next boundary — see _flush_drained_gc)
            self._gc_queue.append(st.doc_id)
        if dt is None:
            return  # never admitted (or this episode already closed)
        self.stats.note_doc_drained(tag, dt)

    # ---- elastic reconfiguration hooks (serve/reshard.py) ----

    def _shard_imbalance(self) -> float:
        """Live-shard occupancy imbalance (peak x live / total, the
        PR 7 ``serve.shard.imbalance`` formula restricted to live
        shards): the reshard coordinator's rebalance trigger."""
        occ = self.pool.shard_occupancy()
        live = [
            occ[s] for s in range(self.pool.n_sh)
            if self.pool.shard_state[s] == "live"
        ]
        total = sum(live)
        if not live or total <= 0:
            return 1.0
        return max(live) * len(live) / total

    def _note_reshard_deferred(self, ops: int) -> None:
        """A migrating doc's lane was pulled from the round: its ops
        defer (re-scheduled next round from the live shard), they are
        never shed."""
        self.stats.deferred_ops += ops

    def _flush_drained_gc(self, force: bool = False) -> None:
        """Batched two-phase reclamation of drained docs (pool record +
        spool/shadow member).  Batching amortizes the manifest fsyncs;
        the final flush at drain end is forced."""
        if not self.drained_gc or not self._gc_queue:
            return
        if not force and len(self._gc_queue) < 32:
            return
        batch, self._gc_queue = self._gc_queue, []
        self.spool_gc_docs += self.pool.gc_drained_docs(batch)

    def _select(self, plan: _Plan) -> None:
        """Pick this macro-round's lanes: {class: [_Lane]}, bounded by
        each bucket's row count, in round-robin order.

        Early exit: once EVERY capacity class's lane set is full, no
        remaining doc can be scheduled this round whatever its class —
        the rest of the rotation stays in place (order preserved) and
        counts as waiting.  On a fleet many times the hot-row budget
        this turns the per-round scan from O(fleet) into O(selected +
        the prefix that filled the buckets)."""
        scheduled: list[int] = []
        deferred: list[int] = []
        live_need: dict[int, int] = {}  # lanes consuming a LIVE row
        open_classes = {
            c for c in self.pool.classes
            if self.pool.buckets[c].usable_rows > 0
        }
        popped_live = 0  # arrived, undrained docs this scan handled
        while self._rr:
            if not open_classes:
                # every class is full: nothing in the unscanned tail
                # can schedule.  Its waiting share is the docs that
                # have ARRIVED and not drained — derived O(1) from the
                # static arrival schedule minus the ended set and the
                # live docs this scan already accounted, so the metric
                # matches what a full scan would have counted.
                arrived = int(np.searchsorted(
                    self._arrivals_sorted, self.round, side="right"
                ))
                plan.waiting += max(
                    0, arrived - len(self._ended) - popped_live
                )
                break
            doc_id = self._rr.popleft()
            st = self.streams[doc_id]
            self._deliver(st)
            if st.remaining == 0:
                self._note_doc_drained(st)
                continue  # drained/shed: drop from the rotation for good
            if st.arrival > self.round:
                deferred.append(doc_id)
                continue
            popped_live += 1
            if st.n_sched <= st.cursor:
                # bounded queue empty under backpressure: wait a round
                plan.waiting += 1
                deferred.append(doc_id)
                continue
            if self.faults is not None:
                dup = self.faults.dup_event(self.round, doc_id, st.cursor)
                if dup is not None:
                    depth = dup.param or min(st.cursor, self.batch)
                    dropped = st.clamp_redelivery(
                        st.cursor - depth, st.cursor
                    )
                    self.stats.dup_ops_dropped += dropped
                    self.stats.faults_injected += 1
                    dup.fire(self.round, doc=doc_id, depth=depth,
                             dropped=dropped)
                    dup.recover()  # clamped, nothing re-applied
                    self._note_fault()
            takes, end = self._sim_takes(st)
            rec = self.pool.docs[doc_id]
            need = rec.n_init + st.ins_before(end)
            cls = self.pool.class_for(max(need, rec.length, 1))
            b = self.pool.buckets[cls]
            lanes = plan.lanes.setdefault(cls, [])
            # lane cap = the LIVE-row budget, consumed by every lane
            # except a resident already serving from a draining shard
            # (it has its row until migrated).  Counting unselected
            # draining residents as capacity would let _place run out
            # of eviction candidates mid-drain, so they are free riders
            # on top of the cap, never part of it.
            on_drain = (rec.cls == cls
                        and not b.live[rec.row // b.Rg])
            need = live_need.get(cls, 0)
            if not on_drain and need >= b.live_rows:
                plan.waiting += 1
                deferred.append(doc_id)
                if b.usable_rows <= b.live_rows:
                    # no draining residents left to free-ride: full
                    open_classes.discard(cls)
                continue
            lanes.append(_Lane(stream=st, takes=takes, end=end))
            if not on_drain:
                need += 1
                live_need[cls] = need
            if need >= b.live_rows and b.usable_rows <= b.live_rows:
                open_classes.discard(cls)
            # the admission edge: one request context per episode
            # (G012 allows context creation here, in the per-DOC
            # selection loop — never in per-op inner loops)
            self.reqtrace.open_request(doc_id, self.round, cap_cls=cls)
            scheduled.append(doc_id)
        # rotation: scheduled docs go to the back; deferred (and any
        # unscanned early-exit tail, already in place) keep order.
        self._rr.extendleft(reversed(deferred))
        self._rr.extend(scheduled)

    def _pick_victim(self, cls: int, selected: set[int],
                     selected_all: set[int]) -> int:
        """Eviction victim in ``cls``: finished docs first, then the
        least recently scheduled pending doc not selected this round.
        Docs scheduled in ANY class this round (e.g. a resident about to
        promote out of ``cls``) are spared when possible — evicting one
        would turn its direct promotion into a spool round-trip — but
        remain the liveness fallback: only this class's own selected set
        is guaranteed to leave a candidate."""
        b = self.pool.buckets[cls]
        candidates = [
            d for d, row in self.pool.residents(cls)
            if d not in selected and b.live[row // b.Rg]
            # draining-shard residents are the reshard coordinator's to
            # move (never shed, never evicted under it) — and evicting
            # one would not free an allocatable row anyway
        ]
        if not candidates:
            raise RuntimeError(
                f"bucket c{cls}: no eviction candidate "
                "(selected set exceeds bucket rows?)"
            )
        preferred = [d for d in candidates if d not in selected_all]
        return min(
            preferred or candidates,
            key=lambda d: (
                self.streams[d].remaining > 0,  # finished docs first
                self.pool.docs[d].last_sched,
                d,
            ),
        )

    def _place(self, plan: _Plan) -> None:
        """Residency bookkeeping for every selected lane (evictions,
        promotions, spool restores, fresh admits) and per-class row
        compaction.  Pure host state — the data moves happen later, at
        the boundary (:meth:`_execute_moves`)."""
        pool = self.pool
        selected_all = {
            l.stream.doc_id for lanes in plan.lanes.values() for l in lanes
        }
        for cls in pool.classes:
            lanes = plan.lanes.get(cls)
            if not lanes:
                continue
            b = pool.buckets[cls]
            selected = {l.stream.doc_id for l in lanes}
            pending: list[tuple[int, tuple]] = []  # (lane idx, source)
            for i, lane in enumerate(lanes):
                rec = pool.docs[lane.stream.doc_id]
                if rec.cls == cls:
                    lane.row = rec.row
                    continue
                if rec.cls is not None:  # promotion out of a smaller class
                    pending.append((i, ("pull", rec.cls, rec.row)))
                    plan.pull_classes.add(rec.cls)
                    b_old = pool.buckets[rec.cls]
                    b_old.rows[rec.row] = None
                    b_old.release_row(rec.row)
                    rec.cls = rec.row = None
                    pool.promotions += 1
                elif lane.stream.doc_id in plan.limbo:
                    # evicted as a SMALLER class's victim earlier this
                    # same round (warm mode defers the deposit to the
                    # boundary, so unlike the two-tier path no spool
                    # marks the state): its bytes still live in the old
                    # bucket row until the moves execute — convert the
                    # eviction into a direct same-round pull, exactly a
                    # promotion (the pre-compose snapshot rule makes
                    # the vacated row safe to read)
                    src = plan.limbo.pop(lane.stream.doc_id)
                    plan.cancelled_evictions.add(lane.stream.doc_id)
                    pending.append((i, ("pull", *src)))
                    pool.promotions += 1
                    self.limbo_pulls += 1
                elif lane.stream.doc_id in pool.warm:
                    # warm hit: the entry composes in at the boundary —
                    # no disk I/O.  Taken NOW (plan time) so nothing
                    # between plan and execute can demote it under us.
                    entry = pool.take_warm_hit(lane.stream.doc_id)
                    pending.append((i, ("warm", entry)))
                elif rec.spool is not None:
                    pending.append((i, ("spool", rec.spool)))
                    pool._set_spool(rec, None)
                    pool.restores += 1
                else:
                    pending.append((i, ("fresh",)))
                    pool.fresh_admits += 1
                self.stats.admissions += 1
            # make room: one victim per missing free row.  With the
            # warm tier armed the victim's row lands there at the
            # boundary (no spool write); the two-tier pool keeps the
            # historical direct-to-spool path.
            warm_mode = pool.warm.budget > 0
            while b.n_free_live < len(pending):
                victim = self._pick_victim(cls, selected, selected_all)
                vrec = pool.docs[victim]
                plan.evictions.append((victim, cls, vrec.row))
                plan.pull_classes.add(cls)
                if not warm_mode:
                    pool._set_spool(vrec, pool._spool_path(victim))
                else:
                    # the state stays in the old row until the moves:
                    # a later (larger) class selecting this doc THIS
                    # round pulls it from there instead of fresh
                    plan.limbo[victim] = (cls, vrec.row)
                b.rows[vrec.row] = None
                b.release_row(vrec.row)
                vrec.cls = vrec.row = None
                pool.evictions += 1
            # ---- occupancy-aware compaction: choose the row tier ----
            # scan kernel AND the fused accelerator form: pow2 K depths
            # bound the compile-shape count (each K is a whole new
            # executable there); fused HOST form: K never keys an
            # executable (the host loops rounds), so the depth trims
            # EXACTLY to the deepest lane and trailing all-PAD slices
            # are never staged at all.
            deepest = max(len(l.takes) for l in lanes)
            if (self.pool.serve_kernel == "fused"
                    and not self.pool.fused_accel_form):
                k_eff = min(deepest, self._k_round)
            else:
                k_eff = min(_pow2ceil(deepest), self._k_round)
            resident_locals = [
                (lane, divmod(lane.row, b.Rg)) for lane in lanes
                if lane.row >= 0
            ]
            n_installs = len(pending)
            chosen_rt = b.R
            relocs: list[tuple[_Lane, int]] = []
            install_rows: list[int] = []
            for rt_total in pool.tiers(cls):
                rt = rt_total // b.n_sh
                fb = [
                    # non-live shards never receive installs or relocs:
                    # their free rows are withdrawn from the tier's
                    # budget (tiers degrade to full-R while a draining
                    # shard still holds scheduled high rows)
                    sorted(l for l in b.free_locals(s) if l < rt)
                    if b.live[s] else []
                    for s in range(b.n_sh)
                ]
                high = [[] for _ in range(b.n_sh)]
                for lane, (s, l) in resident_locals:
                    if l >= rt:
                        high[s].append(lane)
                if any(len(high[s]) > len(fb[s]) for s in range(b.n_sh)):
                    continue
                spare = sum(len(fb[s]) - len(high[s]) for s in range(b.n_sh))
                if spare < n_installs:
                    continue
                chosen_rt = rt_total
                # relocations: high scheduled rows -> lowest free locals
                # on the same shard; installs fill remaining low rows,
                # balanced across shards.
                remaining: list[list[int]] = []
                for s in range(b.n_sh):
                    take = fb[s][: len(high[s])]
                    for lane, dst_l in zip(high[s], take):
                        relocs.append((lane, s * b.Rg + dst_l))
                    remaining.append(fb[s][len(high[s]):])
                for _ in range(n_installs):
                    s = max(
                        range(b.n_sh),
                        key=lambda i: (len(remaining[i]), -i),
                    )
                    install_rows.append(s * b.Rg + remaining[s].pop(0))
                break
            plan.k_eff[cls] = k_eff
            plan.rt[cls] = chosen_rt
            if chosen_rt == b.R:
                install_rows = []  # no tier: plain lowest-row allocation
            inst = plan.installs.setdefault(cls, [])
            for j, (i, source) in enumerate(pending):
                lane = lanes[i]
                rec = pool.docs[lane.stream.doc_id]
                if install_rows:
                    row = install_rows[j]
                    b.take_row(row)
                else:
                    row = b.alloc_row()
                b.rows[row] = rec.doc_id
                rec.cls, rec.row = cls, row
                lane.row = row
                inst.append((rec.doc_id, row, source))
                if self.telemetry is not None and source[0] == "pull":
                    # a promotion that lands on a different mesh shard
                    # than its source row is a cross-shard relocation
                    _, src_cls, src_row = source
                    src_sh = src_row // pool.buckets[src_cls].Rg
                    if src_sh != row // b.Rg:
                        self.telemetry.shards.note_relocation(
                            row // b.Rg
                        )
            for lane, dst in relocs:
                rec = pool.docs[lane.stream.doc_id]
                src = rec.row
                plan.pull_classes.add(cls)
                inst.append((rec.doc_id, dst, ("pull", cls, src)))
                b.take_row(dst)
                b.rows[dst] = rec.doc_id
                b.rows[src] = None
                b.release_row(src)
                rec.row = dst
                lane.row = dst

    def _plan(self) -> _Plan | None:
        """One macro-round's full host plan, or None when drained.
        Advances the round clock over arrival-wait gaps.  The macro
        depth is FROZEN per plan (``_k_round``): a fault that trips
        degradation mid-selection (e.g. a dup event inside ``_select``)
        must not shrink K under lanes already sized for the old depth —
        the fallback takes effect from the next plan."""
        while True:
            self._k_round = self.effective_k
            self._planned_degraded = self._degrade_left > 0
            self._feed_rotation()
            plan = _Plan(base_round=self.round)
            self._select(plan)
            if plan.lanes:
                self._place(plan)
                return plan
            if self._lazy:
                # unarrived docs are exactly the unfed tail of the
                # order array — the next arrival is O(1), no scan
                if self._order_ptr >= len(self._order):
                    return None
                self.round = int(
                    self._order_arrivals[self._order_ptr]
                )
                continue
            pending = [
                s.arrival for s in self.streams.values()
                if s.remaining and s.arrival > self.round
            ]
            if not pending:
                return None
            self.round = min(pending)  # idle: jump to the next arrival

    def _feed_rotation(self) -> None:
        """Streaming construction: admit every doc whose arrival round
        has come into the rotation (ids only — materialization waits
        for first selection or an off-drain construct prefetch)."""
        if not self._lazy:
            return
        n = len(self._order)
        p = self._order_ptr
        while p < n and self._order_arrivals[p] <= self.round:
            self._rr.append(int(self._order[p]))
            p += 1
        self._order_ptr = p

    # ---- staging (host tensorize; overlaps device execution) ----

    def _stage(self, plan: _Plan) -> dict[int, tuple]:
        tensors: dict[int, tuple] = {}
        B = self.batch
        dt_kind, dt_pos, dt_rlen, dt_slot = self.pool.op_dtypes
        for cls, lanes in plan.lanes.items():
            K, Rt = plan.k_eff[cls], plan.rt[cls]
            b = self.pool.buckets[cls]
            rt = Rt // b.n_sh
            # staged in the pool's packed lane dtypes: stream arrays
            # are already packed (prepare_streams), so every copy here
            # is narrow-to-narrow — no silent wrap is possible.  PAD
            # lanes carry slot0 = 0 (never read; kind == PAD gates it).
            kind = np.full((K, Rt, B), PAD, dt_kind)
            pos = np.zeros((K, Rt, B), dt_pos)
            rlen = np.zeros((K, Rt, B), dt_rlen)
            slot0 = np.zeros((K, Rt, B), dt_slot)
            for lane in lanes:
                st = lane.stream
                s, l = divmod(lane.row, b.Rg)
                r = s * rt + l  # sliced row index
                c = st.cursor
                for k, take in enumerate(lane.takes):
                    kind[k, r, :take] = st.kind[c:c + take]
                    pos[k, r, :take] = st.pos[c:c + take]
                    rlen[k, r, :take] = st.rlen[c:c + take]
                    slot0[k, r, :take] = st.slot0[c:c + take]
                    c += take
            tensors[cls] = (kind, pos, rlen, slot0)
        return tensors

    # ---- fault firing + repair (serve/faults.py + serve/journal.py) ----

    def _maybe_stall(self, rnd: int) -> None:
        """Host staging stall fault: sleep the staging path."""
        hit = self.faults.stall_event(rnd)
        if hit is None:
            return
        ev, secs = hit
        time.sleep(secs)
        ev.fire(rnd, ms=secs * 1e3)
        ev.recover()  # a stall is absorbed, not repaired
        self.stats.stall_rounds += 1
        self.stats.faults_injected += 1
        self._note_fault()

    def _fire_overflow(self) -> None:
        """Queue-overflow fault: the producer bursts past the bounded
        cap and the scheduler makes the explicit shed/defer decision."""
        if self.queue_cap <= 0:
            return
        ev = self.faults.overflow_event(self.round)
        if ev is None:
            return
        cands = sorted(
            d for d, s in self.streams.items()
            if s.remaining > 0 and s.delivered is not None
        )
        if self.overflow_policy == "shed" and self.reshard is not None:
            # admission stays open during a reshard: a doc mid-move
            # briefly defers, it is NEVER the shed victim
            migrating = self.reshard.migrating_docs()
            if migrating:
                cands = [d for d in cands if d not in migrating]
        if not cands:
            return  # stays pending; retried next round
        deep = [d for d in cands
                if self.streams[d].remaining > self.queue_cap]
        doc = self.faults.pick(deep or cands)
        st = self.streams[doc]
        burst = ev.param or self.faults.plan.burst or 4 * self.queue_cap
        lim = st.cursor + self.queue_cap
        want = min(st.n_total, lim + burst)
        self.stats.overflow_events += 1
        self.stats.faults_injected += 1
        self._note_fault()
        shed = 0
        if self.overflow_policy == "shed":
            # load-shed: tail-drop the session's remaining ops past the
            # cap — explicit, surfaced loss (the doc becomes lossy)
            keep = min(st.n_total, lim)
            shed = st.n_total - keep
            if shed:
                st.limit = keep
                st.lossy = True
                self.stats.shed_ops += shed
                if self.journal:
                    self.journal.event(
                        "shed", r=self.round, doc=doc, at=keep, ops=shed
                    )
                if st.remaining == 0:
                    self._note_doc_drained(st)  # shed ended the stream
        else:
            # defer: the bounded queue refuses the burst; the producer
            # holds the excess and redelivers under backpressure
            shed = 0
            ev.detail["deferred"] = self._push_delivery(st, want)
        ev.fire(self.round, doc=doc, burst=burst,
                policy=self.overflow_policy, shed=shed)
        ev.recover()  # the decision IS the recovery

    # ---- predictive prefetch (cold→warm ahead of the admission plan;
    # every hot-thread touch here is non-blocking by contract, G016) --

    def _harvest_prefetch(self) -> None:
        """Adopt completed rehydrates into the warm tier (start of
        round, before planning — so this round's admissions see them).
        Stale payloads — the doc went hot/warm while the read flew, or
        its spool generation moved — are dropped and counted; the doc
        simply stays on whatever path it took without the prefetcher."""
        pf = self.pool.prefetcher
        if pf is None:
            return
        for payload in pf.drain():
            doc_id = payload["doc"]
            self._prefetch_inflight.pop(doc_id, None)
            if payload["error"] is not None:
                # damaged/vanished spool (or a construct builder that
                # raised): the synchronous admission path owns
                # detection + heal; nothing to do here
                continue
            if payload.get("kind") == "construct":
                # a worker-built stream (streaming construction):
                # install it unless the doc already materialized
                # synchronously while the construction flew
                if not self.streams.adopt(doc_id, payload):
                    self.prefetch_wasted += 1
                continue
            if not self.pool.store_prefetched(
                doc_id, payload["row"], payload["length"],
                payload["nvis"], round_no=self.round,
                gen=payload["gen"],
            ):
                self.prefetch_wasted += 1  # superseded (went hot/warm
                # on its own, or the read raced a re-eviction)

    def _plan_prefetch(self) -> None:
        """Submit the next admission horizon's cold docs for async
        rehydrate: the front of the round-robin rotation IS the
        scheduler's look-ahead plan (deterministic order), bounded by
        the arrival model (docs arriving within the next macro-round's
        span).  The ``prefetch_miss`` chaos kind drops the whole
        planned batch — admission then takes the synchronous path,
        which must stay verify-green."""
        pf = self.pool.prefetcher
        if pf is None:
            return
        pool = self.pool
        horizon = self.round + self._k_round
        # reap in-flight entries whose results never arrived (dropped
        # by the worker's bounded publish during a wedged round): left
        # in place they would pin the submission budget forever
        reap_before = self.round - 32 * max(1, self._k_round)
        stale = [
            (d, seq) for d, (r0, seq) in self._prefetch_inflight.items()
            if r0 < reap_before
        ]
        if stale:
            for d, _seq in stale:
                del self._prefetch_inflight[d]
            # reap BY SEQ: a payload that merely outlived the reaper is
            # dropped at harvest without a second inflight decrement
            pf.note_lost([seq for _d, seq in stale])
        # outstanding work is bounded by the admission horizon AND the
        # worker's queue capacity (never more reads in flight than the
        # result queue can absorb), NOT by warm free space: a full
        # tier makes room for predicted docs by demoting its stalest
        # entries (store_prefetched)
        space = min(self._prefetch_lookahead, pool.warm.budget,
                    pf.capacity) - len(self._prefetch_inflight)
        # each entry: ("spool", doc, path, gen) — a cold rehydrate — or
        # ("construct", doc) — an off-drain stream construction for a
        # genesis doc the rotation will reach (streaming mode only)
        wanted: list[tuple] = []
        scanned = 0
        for doc_id in self._rr:
            scanned += 1
            if scanned > self._prefetch_lookahead or len(wanted) >= space:
                break
            if doc_id in self._prefetch_inflight:
                continue
            rec = pool.docs.get(doc_id) if self._lazy \
                else pool.docs[doc_id]
            if rec is None:
                # genesis doc already fed into the rotation: build its
                # stream off-drain (it is arrived by the feed
                # invariant, so it is always within the horizon)
                wanted.append(("construct", doc_id))
                continue
            if rec.spool is None or rec.cls is not None \
                    or doc_id in pool.warm:
                continue
            st = self.streams[doc_id]
            if st.remaining == 0 or st.arrival > horizon:
                continue
            wanted.append(
                ("spool", doc_id, rec.spool, pool.spool_gen(doc_id))
            )
        if self._lazy:
            # look PAST the fed rotation: genesis docs arriving within
            # the horizon get their streams built before their feed
            p = self._order_ptr
            n = len(self._order)
            while p < n and len(wanted) < space \
                    and scanned <= self._prefetch_lookahead:
                if self._order_arrivals[p] > horizon:
                    break
                d = int(self._order[p])
                p += 1
                scanned += 1
                if d in self._prefetch_inflight or d in pool.docs:
                    continue
                wanted.append(("construct", d))
        if not wanted:
            return
        if self.faults is not None:
            ev = self.faults.prefetch_miss_event(self.round)
            if ev is not None:
                # the planned prefetches are DROPPED: admission falls
                # back to synchronous rehydrate (the G016 contract —
                # a miss never blocks, it just pays the disk read)
                self.prefetch_missed += len(wanted)
                self.stats.faults_injected += 1
                ev.fire(self.round, dropped=len(wanted))
                ev.recover()  # the sync fallback IS the recovery
                self._note_fault()
                if self.telemetry is not None:
                    self.telemetry.note_event(
                        "tier", why="prefetch_miss", round=self.round,
                        dropped=len(wanted),
                    )
                return
        for item in wanted:
            if item[0] == "spool":
                _, doc_id, path, gen = item
                seq = pf.submit(doc_id, path, gen)
            else:
                _, doc_id = item
                seq = pf.submit_construct(
                    doc_id, self.streams.builder(doc_id)
                )
            if seq:
                self._prefetch_inflight[doc_id] = (self.round, seq)

    def _fire_tier_pressure(self) -> None:
        """The ``tier_evict_pressure`` chaos kind: force warm-tier
        churn under load — LRU entries demoted to the compressed cold
        spool so following admissions pay the cold path (and the
        prefetcher has real misses to hide).  Pending until the warm
        tier holds anything.  The poll stays open (a per-round no-op
        fence crossing would drown the counters — the _maybe_snapshot
        lesson); only the actual demotion below is the fence."""
        ev = self.faults.tier_pressure_event(self.round)
        if ev is None:
            return
        if not len(self.pool.warm):
            return  # stays pending; retried next round
        self._tier_pressure_barrier(ev)

    @fenced
    def _tier_pressure_barrier(self, ev) -> None:  # graftlint: fence=chaos
        """Execute one forced warm→cold churn event (compressed spool
        writes for unshadowed LRU entries — disk work, hence the
        declared chaos fence, like the spool-tear injector)."""
        n = ev.param or max(1, len(self.pool.warm) // 2)
        demoted = self.pool.warm_pressure(n)
        self.stats.faults_injected += 1
        ev.fire(self.round, demoted=demoted)
        ev.recover()  # churn is absorbed, not repaired
        self._note_fault()
        if self.telemetry is not None:
            self.telemetry.note_event(
                "tier", why="evict_pressure", round=self.round,
                demoted=demoted,
            )

    def _all_residents(self) -> list[tuple[int, int]]:
        return [
            (d, row) for cls in self.pool.classes
            for d, row in self.pool.residents(cls)
        ]

    @fenced
    def _fire_spool_fault(self, plan: _Plan) -> None:  # graftlint: fence=chaos
        """Corrupt/truncate an eviction spool on disk.  Prefers an
        existing spool of a doc with pending ops (its restore — and so
        the detection — is guaranteed); with none live, tears a spool as
        it is written by evicting a non-scheduled pending resident."""
        ev = self.faults.spool_event(self.round)
        if ev is None:
            return
        pool = self.pool
        cands = sorted(
            d for d, rec in pool.docs.items()
            if rec.spool is not None and os.path.exists(rec.spool)
            and self.streams[d].remaining > 0
        )
        if not cands:
            scheduled = {
                l.stream.doc_id
                for lanes in plan.lanes.values() for l in lanes
            }
            evictable = sorted(
                d for d, _row in self._all_residents()
                if d not in scheduled and self.streams[d].remaining > 0
            )
            if not evictable:
                return  # stays pending; retried next round
            victim = self.faults.pick(evictable)
            pool.evict(victim)  # a boundary sync, like any eviction
            cands = [victim]
        doc = self.faults.pick(cands)
        detail = self.faults.corrupt_file(pool.docs[doc].spool, ev.kind)
        ev.fire(self.round, doc=doc, **detail)
        self.stats.faults_injected += 1

    def _quarantine(self, doc_id: int, reason: str) -> None:
        """Isolate a document that cannot be repaired: shed its
        remaining ops, free its row, and keep the fleet serving.  The
        doc is marked lossy (excluded from byte-verification) and the
        decision is journaled — recovery must re-apply it."""
        st = self.streams[doc_id]
        rec = self.pool.docs[doc_id]
        shed = max(0, st.remaining)
        st.limit = st.cursor
        st.lossy = True
        self.stats.shed_ops += shed
        if rec.cls is not None:
            b = self.pool.buckets[rec.cls]
            b.rows[rec.row] = None
            b.release_row(rec.row)
            rec.cls = rec.row = None
        self.pool._set_spool(rec, None)
        self.pool.warm.take(doc_id)  # a quarantined doc holds no tier
        self._dead_lanes.add(doc_id)
        self._note_doc_drained(st, tag="quarantined")
        self.stats.quarantines.append({
            "doc": doc_id, "round": self.round, "reason": reason,
            "shed_ops": shed,
        })
        if self.journal:
            self.journal.event(
                "quarantine", r=self.round, doc=doc_id, at=st.cursor,
                ops=shed, reason=reason[:120],
            )

    @fenced
    def _heal_spool(self, doc_id: int, cls: int, err: str):  # graftlint: fence=chaos
        """A spool failed its integrity check on restore: rebuild the
        doc's row at its applied cursor from the last snapshot base (or
        from scratch — streams are deterministic) through the macro
        replay path.  Returns ``(doc_row, length, nvis)`` or None after
        quarantining an unrepairable doc."""
        st = self.streams[doc_id]
        rec = self.pool.docs[doc_id]
        self._note_fault()
        ev = None
        if self.faults is not None:
            for e in self.faults.plan.events:
                if (e.kind in ("spool_corrupt", "spool_truncate")
                        and e.fired and not e.recovered
                        and e.detail.get("doc") == doc_id):
                    ev = e
                    break
        try:
            if self.faults is not None and self.faults.poisoned(doc_id):
                raise RuntimeError("rebuild poisoned by fault plan")
            with span("serve.recover.spool", doc=doc_id):
                base = self._bases.base(doc_id)
                row_v, L, nv, disp = rebuild_doc(
                    st, cls, base, st.cursor, n_init=rec.n_init,
                    batch=self.batch, batch_chars=self.batch_chars,
                    nbits=self.nbits, macro_k=self.effective_k,
                )
            start = min(base[3], st.cursor) if base is not None else 0
            self.stats.recoveries += 1
            self.stats.ops_replayed += st.cursor - start
            self.stats.replay_dispatches += disp
            self.stats.mttr_rounds.append(max(1, disp))
            if ev is not None:
                ev.recover()
            if self.journal:
                self.journal.event(
                    "heal", r=self.round, doc=doc_id,
                    ops=st.cursor - start, why="spool",
                )
            if self.telemetry is not None:
                self.telemetry.note_event(
                    "recovery", round=self.round, doc=doc_id,
                    why="spool", ops=st.cursor - start,
                )
            return row_v, L, nv
        except Exception as e2:  # rebuild itself failed: isolate the doc
            self._quarantine(
                doc_id, f"spool unreadable ({err}); rebuild failed: {e2}"
            )
            if ev is not None:
                ev.detail["quarantined"] = True
            return None
        finally:
            self._bases.release()  # don't pin snapshot arrays post-heal

    @fenced
    def _recover_class(  # graftlint: fence=chaos
            self, cls: int, plan: _Plan, ev) -> None:
        """Device-state loss mid-macro-round: the class's bucket is gone.
        This round's staged ops for the class never became durable —
        their lanes are dropped un-advanced (the WAL already recorded
        them; the docs simply get rescheduled).  Every resident row is
        rebuilt at its applied cursor from snapshot base + stream replay
        and the bucket is re-uploaded in one compose."""
        pool = self.pool
        b = pool.buckets[cls]
        plan.lanes.pop(cls, None)  # not applied: do not advance cursors
        affected = pool.residents(cls)
        doc_w = np.full((b.R, b.C), 2, np.int32)
        len_w = np.zeros(b.R, np.int32)
        nvis_w = np.zeros(b.R, np.int32)
        replayed = 0
        disp_total = 0
        disp_max = 0
        self._note_fault()
        for doc_id, row in affected:
            st = self.streams[doc_id]
            rec = pool.docs[doc_id]
            try:
                if self.faults is not None and self.faults.poisoned(doc_id):
                    raise RuntimeError("rebuild poisoned by fault plan")
                base = self._bases.base(doc_id)
                row_v, L, nv, disp = rebuild_doc(
                    st, cls, base, st.cursor, n_init=rec.n_init,
                    batch=self.batch, batch_chars=self.batch_chars,
                    nbits=self.nbits, macro_k=self.effective_k,
                )
            except Exception as e:
                self._quarantine(doc_id, f"device loss; rebuild failed: {e}")
                continue
            doc_w[row] = row_v
            len_w[row] = L
            nvis_w[row] = nv
            start = min(base[3], st.cursor) if base is not None else 0
            replayed += st.cursor - start
            disp_total += disp
            disp_max = max(disp_max, disp)
        pool.upload_bucket(cls, doc_w, len_w, nvis_w)
        self._bases.release()  # whole-class pass done: drop cached states
        self.stats.recoveries += 1
        self.stats.ops_replayed += replayed
        self.stats.replay_dispatches += disp_total
        self.stats.mttr_rounds.append(max(1, disp_max))
        self.stats.faults_injected += 1
        ev.fire(self.round, cls=cls, docs=len(affected),
                replayed_ops=replayed)
        ev.recover()
        if self.journal:
            self.journal.event(
                "device_loss", r=self.round, cls=cls, docs=len(affected),
                ops=replayed,
            )
        if self.telemetry is not None:
            self.telemetry.note_event(
                "recovery", round=self.round, cls=cls,
                why="device_loss", ops=replayed,
            )

    def finalize_faults(self) -> None:
        """End-of-drain sweep: a corrupted spool whose doc was never
        rehydrated again is healed NOW (rebuild + rewrite the spool), so
        a chaos run never ends with an undecodable doc or a fired fault
        left unrecovered.  Durability kinds close here too: a torn GC
        pass still pending is completed (the exact repair the next open
        would perform), and a corrupted delta link is proven recoverable
        by dry-running the chain-fallback snapshot selection."""
        for e in self.faults.plan.events:
            if e.kind == "crash_compact" and e.fired and not e.recovered \
                    and self.journal is not None:
                n = self.journal.finish_torn_gc()
                e.recover(completed="finalize", segments=n)
                if e is self._pending_gc_ev:
                    self._pending_gc_ev = None
            if e.kind == "delta_corrupt" and e.fired and not e.recovered \
                    and self.journal is not None:
                used, fallbacks = probe_recovery(self.journal.dir)
                if used is not None:
                    # a usable snapshot materialized despite the damage:
                    # either the walk fell back below the corrupt link
                    # (fallbacks > 0) or a later full barrier re-rooted
                    # the chain past it — both are the designed repair
                    e.recover(fallback_to=used, fallbacks=fallbacks)
                if self.telemetry is not None:
                    self.telemetry.note_event(
                        "recovery_probe", used=used, fallbacks=fallbacks,
                    )
        for e in self.faults.plan.events:
            if e.kind not in ("spool_corrupt", "spool_truncate"):
                continue
            if not e.fired or e.recovered:
                continue
            doc_id = e.detail.get("doc")
            rec = self.pool.docs.get(doc_id)
            st = self.streams.get(doc_id)
            if rec is None or st is None:
                continue
            if rec.spool is None or not os.path.exists(rec.spool):
                e.recover()  # superseded: doc resident again
                continue
            try:
                load_state(rec.spool)
                e.recover()  # damage missed the live bytes
                continue
            except CorruptCheckpointError as err:
                healed = self._heal_spool(
                    doc_id, self.pool.class_for(max(rec.length, 1)),
                    str(err),
                )
            if healed is None:
                continue  # quarantined (reported separately)
            row_v, L, nv = healed
            self.pool._set_spool(
                rec, self.pool.spool_save(doc_id, row_v, L, nv)
            )
            e.recover()

    # ---- boundary execution (the only device syncs) ----

    @fenced
    def _execute_moves(self, plan: _Plan) -> None:  # graftlint: fence
        """Apply the plan's row movement: pull affected buckets once
        (syncing with any in-flight macro step), write eviction spools,
        compose installs on host, upload each touched bucket once.  A
        spool that fails its CRC here is repaired in place
        (:meth:`_heal_spool`) — or its doc quarantined."""
        pool = self.pool
        snaps = {
            cls: pool.pull_bucket(cls) for cls in sorted(plan.pull_classes)
        }
        warm_mode = pool.warm.budget > 0
        demoted = 0
        for doc_id, cls, row in plan.evictions:
            if doc_id in plan.cancelled_evictions:
                continue  # re-admitted this round: the install pulls it
            doc, length, nvis = snaps[cls]
            if warm_mode:
                # hot→warm: a trimmed host copy, no disk I/O; LRU
                # overflow demotes to the compressed cold spool
                demoted += pool.warm_deposit(
                    doc_id, doc[row], int(length[row]), int(nvis[row]),
                    last_sched=pool.docs[doc_id].last_sched,
                )
            else:
                pool.spool_save(
                    doc_id, doc[row], int(length[row]), int(nvis[row])
                )
        if warm_mode:
            # trim any harvest-time prefetch overflow too: disk writes
            # belong inside this fence, so store_prefetched defers its
            # budget enforcement here
            demoted += pool._enforce_warm_budget()
        if demoted and self.telemetry is not None:
            self.telemetry.note_event(
                "tier", why="warm_overflow", round=self.round,
                demoted=demoted,
            )
        for cls, items in plan.installs.items():
            if not items:
                continue
            if cls in snaps:
                doc_s, len_s, nvis_s = snaps[cls]
            else:
                doc_s, len_s, nvis_s = pool.pull_bucket(cls)
            # writable copies: sources always read the pre-compose
            # snapshot, so a row can be both vacated and refilled in one
            # boundary without ordering hazards.
            doc_w = np.array(doc_s)
            len_w = np.array(len_s)
            nvis_w = np.array(nvis_s)
            C = self.pool.buckets[cls].C
            for doc_id, row, source in items:
                rec = pool.docs[doc_id]
                if source[0] == "fresh":
                    doc_w[row] = _fresh_row_np(C, rec.n_init)
                    len_w[row] = nvis_w[row] = rec.n_init
                elif source[0] == "warm":
                    # warm compose: pure memory, no disk I/O
                    entry = source[1]
                    L = entry.length
                    doc_w[row, :L] = entry.doc_row[:L]
                    doc_w[row, L:] = 2
                    len_w[row] = L
                    nvis_w[row] = entry.nvis
                elif source[0] == "spool":
                    try:
                        st = load_state(source[1])
                    except CorruptCheckpointError as e:
                        healed = self._heal_spool(doc_id, cls, str(e))
                        try:
                            os.unlink(source[1])
                        except OSError:
                            pass
                        if healed is None:  # quarantined: scratch row
                            doc_w[row] = _fresh_row_np(C, rec.n_init)
                            len_w[row] = nvis_w[row] = rec.n_init
                        else:
                            row_v, L, nv = healed
                            doc_w[row, :L] = row_v[:L]
                            doc_w[row, L:] = 2
                            len_w[row] = L
                            nvis_w[row] = nv
                        continue
                    # deferred unlink (see DocPool.admit): the spool
                    # stays on disk as a stale file until the next
                    # eviction's atomic save_state replaces it — the
                    # doc is never without a durable copy mid-flight
                    L = int(st.length[0])
                    doc_w[row, :L] = st.doc[0, :L]
                    doc_w[row, L:] = 2
                    len_w[row] = L
                    nvis_w[row] = int(st.nvis[0])
                else:  # ("pull", src_cls, src_row)
                    _, src_cls, src_row = source
                    sdoc, slen, snvis = snaps[src_cls]
                    L = int(slen[src_row])
                    doc_w[row, :L] = sdoc[src_row, :L]
                    doc_w[row, L:] = 2
                    len_w[row] = L
                    nvis_w[row] = int(snvis[src_row])
            pool.upload_bucket(
                cls, doc_w, len_w, nvis_w,
                dirty_rows=[row for _d, row, _s in items],
            )
        # drained-doc reclamation rides the same boundary: disk work
        # belongs here, not in the per-doc drain notification
        self._flush_drained_gc()

    # ---- dispatch + mirrors ----

    def _dispatch(self, plan: _Plan, tensors: dict[int, tuple]) -> bool:
        compiled = False
        for cls, (kind, pos, rlen, slot0) in tensors.items():
            compiled |= self.pool.macro_step(
                cls, kind, pos, rlen, slot0, nbits=self.nbits
            )
            self.stats.slices += plan.k_eff[cls]
            self.stats.staged_cells += kind.size
            if self.faults is not None:
                ev = self.faults.device_loss_event(self.round, cls)
                if ev is not None:
                    with span("serve.recover.class", cls=cls):
                        self._recover_class(cls, plan, ev)
        return compiled

    def _advance(self, plan: _Plan) -> None:
        """Host mirrors after dispatch: the staged ops WILL be applied,
        and length/cursor evolve deterministically, so no sync is needed
        to keep scheduling exact.  Lanes of a class that lost its device
        state (popped from the plan) and quarantined docs do NOT
        advance — their ops are simply rescheduled or shed."""
        lanes_used = 0
        n_sh = self.pool.n_sh
        sh_lanes = [0] * n_sh
        sh_ops = [0] * n_sh
        sh_units = [0] * n_sh
        for cls, lanes in plan.lanes.items():
            Rg = self.pool.buckets[cls].Rg
            for lane in lanes:
                st = lane.stream
                if st.doc_id in self._dead_lanes:
                    continue
                rec = self.pool.docs[st.doc_id]
                ops_d = lane.end - st.cursor
                units_d = (
                    st.units_before(lane.end) - st.units_before(st.cursor)
                )
                self.stats.ops += ops_d
                self.stats.unit_ops += units_d
                # shard attribution is host arithmetic: the lane's mesh
                # shard is its row's shard group (rows never straddle)
                s = lane.row // Rg
                sh_lanes[s] += 1
                sh_ops[s] += ops_d
                sh_units[s] += units_d
                st.cursor = lane.end
                rec.length = rec.n_init + st.ins_before(lane.end)
                rec.last_sched = plan.base_round
                lanes_used += 1
                if st.remaining == 0:
                    self._note_doc_drained(st)
        self._dead_lanes.clear()
        total_lanes = sum(b.R for b in self.pool.buckets.values())
        occ = lanes_used / total_lanes
        self.stats.occupancy.observe(occ)
        self.stats.queue_depth.observe(plan.waiting)
        self._last_occ = occ
        self._last_queue = plan.waiting
        self._sh_lanes, self._sh_ops, self._sh_units = (
            sh_lanes, sh_ops, sh_units
        )
        if self._planned_degraded:
            self.stats.degraded_rounds += 1
            self._degrade_left -= 1
        if self._bp_round:
            self.stats.backpressure_rounds += 1
            self._bp_round = False
        self.pool.update_tier_gauges()
        self.round = plan.base_round + max(plan.k_eff.values())
        self._n_rounds += 1

    def _maybe_snapshot(self) -> None:
        """Cadence gate for the snapshot barrier.  PR 4 fenced THIS
        function, which made the declared fence cross every round even
        in journal-less runs where it never syncs — the sanitizer's
        counters showed pure no-op crossings drowning the ground truth.
        Repaired: the cadence check stays open, only the actual barrier
        below is the fence."""
        self._snapped = False
        if self.journal is None or self.snapshot_every <= 0:
            return
        if self._n_rounds % self.snapshot_every:
            return
        with span("serve.snapshot"):
            self._snapshot_barrier()
        self._snapped = True

    @fenced
    def _snapshot_barrier(self) -> None:  # graftlint: fence=journal
        """Periodic fleet snapshot barrier (journal mode): persist a
        consistent set — a chain-rooting FULL barrier every
        ``snapshot_full_every``-th time, a dirty-rows-only DELTA
        (CRC-chained to its base) in between — then run the WAL
        segment GC pass the barrier just made safe.  The barrier is a
        forced sync — its round is flagged so steady-state latency
        quantiles exclude it, like compile rounds."""
        t0 = time.perf_counter()
        self._barrier_count += 1
        kind = "full"
        if (self.snapshot_full_every > 1
                and (self._barrier_count - 1) % self.snapshot_full_every):
            kind = "delta"
        d, m = write_snapshot(
            self.journal.dir, self.pool, self.streams, self.round,
            keep=self.snapshot_keep, kind=kind,
        )
        self.stats.snapshots += 1
        self.stats.snapshot_time += time.perf_counter() - t0
        # write_snapshot may have silently re-rooted (no usable base /
        # depth cap) — the committed manifest is the truth
        kind = m["kind"]
        depth = int(m["depth"])
        if kind == "full":
            self.stats.snapshots_full += 1
        else:
            self.stats.snapshots_delta += 1
        self._g_chain_depth.set(depth)
        self.journal.note_snapshot(d)
        self._bases.release()  # the new barrier may have pruned old dirs
        if self.telemetry is not None:
            self.telemetry.note_event(
                "snapshot", round=self.round, snap_kind=kind,
                depth=depth,
            )
        # ---- WAL segment GC: safe exactly now (the barrier committed).
        # Covered round = the OLDEST retained snapshot's round, not
        # this barrier's: chain fallback may land recovery on any
        # retained snapshot and its redo tail (incl. journaled
        # quarantine/shed decisions) starts there.  Crash-safe
        # two-phase delete; the chaos injector's crash_compact kills
        # it between the GC-manifest write and the unlinks.  The
        # barrier's own "snap" marker is appended AFTER the pass:
        # compact rolls the active file first, and a marker inside the
        # sealed segment at the covered round would pin it for one
        # extra barrier. ----
        floor = retained_floor(self.journal.dir)
        info = self.journal.compact(
            self.round if floor is None else floor,
            crash_hook=self._gc_crash_hook,
        )
        self.journal.event(
            "snap", r=self.round, dir=os.path.basename(d),
            snap_kind=kind, depth=depth,
        )
        if not info["crashed"]:
            # a pass killed mid-flight did NOT complete — the gauge
            # answers "when did a compaction last finish"
            self._g_last_compact.set(self.round)
        if info["torn_completed"] and self._pending_gc_ev is not None:
            self._pending_gc_ev.recover(
                completed_round=self.round,
                segments=info["torn_completed"],
            )
            self._pending_gc_ev = None
        if self.telemetry is not None and (
                info["deleted"] or info["torn_completed"]
                or info["crashed"]):
            self.telemetry.note_event("compaction", **info)
        if self.faults is not None:
            self._fire_delta_corrupt()

    def _gc_crash_hook(self) -> bool:
        """The ``crash_compact`` kill point: polled by the journal's GC
        pass between its manifest commit and the unlinks.  Returning
        True abandons the pass mid-flight — exactly the torn state the
        next open/compaction/recovery must repair."""
        if self.faults is None:
            return False
        ev = self.faults.compact_crash_event(self.round)
        if ev is None:
            return False
        ev.fire(self.round, stage="post_manifest_pre_unlink")
        self.stats.faults_injected += 1
        self._note_fault()
        self._pending_gc_ev = ev
        return True

    def _fire_delta_corrupt(self) -> None:
        """The ``delta_corrupt`` chaos kind: flip bytes inside the
        newest delta snapshot's member (runs inside the barrier fence —
        pure file damage).  Stays pending until a delta exists.
        Recovery must fall back down the chain — proven by
        :meth:`finalize_faults`'s probe or the bench recovery leg."""
        ev = self.faults.delta_corrupt_event(self.round)
        if ev is None:
            return
        jd = self.journal.dir
        target = None
        for snap in reversed(list_snapshots(jd)):
            m = _read_manifest(os.path.join(jd, snap))
            if m is not None and m.get("kind") == "delta":
                target = snap
                break
        if target is None:
            return  # no delta committed yet: retried next barrier
        sd = os.path.join(jd, target)
        members = sorted(
            f for f in os.listdir(sd)
            if f.startswith("delta_") and f.endswith(".npz")
        )
        path = os.path.join(
            sd, members[0] if members else "MANIFEST.json"
        )
        detail = self.faults.corrupt_file(path, "delta_corrupt")
        ev.fire(self.round, dir=target,
                member=os.path.basename(path), **detail)
        self.stats.faults_injected += 1
        self._note_fault()

    # ---- continuous telemetry taps (host-only; see obs/timeseries) ----

    def _cum_counters(self) -> dict:
        """Cumulative counters the time-series recorder delta-encodes
        into windows.  Keys are the fixed ``obs/timeseries.py
        CUM_KEYS`` set."""
        s = self.stats
        return {
            "ops": s.ops,
            "unit_ops": s.unit_ops,
            "shed": s.shed_ops,
            "deferred": s.deferred_ops,
            "quarantines": len(s.quarantines),
            "dup_dropped": s.dup_ops_dropped,
            "evictions": self.pool.evictions,
            "restores": self.pool.restores,
            "promotions": self.pool.promotions,
            "recoveries": s.recoveries,
            "journal_bytes": (
                self.journal.bytes_total if self.journal else 0
            ),
            "fence_entries": entries_total(),
        }

    def status_fields(self) -> dict:
        """The ``/status.json`` snapshot: where the drain is right now,
        including its fault/degraded state.  Plain scalars only — the
        status server serializes whatever is published verbatim."""
        s = self.stats
        out = {
            "phase": "serving",
            "round": self.round,
            "rounds": self._n_rounds,
            "occupancy": self._last_occ,
            "queue_depth": self._last_queue,
            "ops": s.ops,
            "unit_ops": s.unit_ops,
            "patches": s.patches,
            "shed_ops": s.shed_ops,
            "deferred_ops": s.deferred_ops,
            "quarantines": len(s.quarantines),
            "degraded": self._degrade_left > 0,
            "faults_seen": s.faults_seen,
            "faults_injected": s.faults_injected,
            "recoveries": s.recoveries,
            "snapshots": s.snapshots,
            "done": False,
        }
        if self.pool.warm.budget > 0:
            # live tier-residency view (small scalars; the gauges
            # carry the same numbers on /metrics)
            res = self.pool.tier_status()
            res["prefetch_wasted"] = self.prefetch_wasted
            res["prefetch_missed"] = self.prefetch_missed
            out["residency"] = res
        if self.journal is not None:
            # live bounded-footprint view: WAL segments, bytes since
            # the last committed barrier, chain depth, last GC round
            # (gauge/counter reads only — no disk walk per round)
            d = self.journal.status_fields()
            d["chain_depth"] = int(self._g_chain_depth.value)
            d["last_compaction_round"] = int(self._g_last_compact.value)
            d["snapshots_full"] = s.snapshots_full
            d["snapshots_delta"] = s.snapshots_delta
            out["durability"] = d
        if self.slo is not None:
            # burn rates + top-K slowest docs with segment breakdowns
            # (pure host arithmetic over pre-registered state, G013)
            out["slo"] = self.slo.status_fields()
        if self.reshard is not None:
            # live migration view: state machine position, pending doc
            # count, moved/deferred tallies (the gauges mirror these
            # on /metrics as serve.reshard.*)
            out["reshard"] = self.reshard.status_fields()
        return out

    # ---- driver ----

    def run_round(self) -> bool:  # graftlint: thread=hot
        """One macro-round (plan -> WAL record -> stage -> boundary
        moves -> one async dispatch per class).  Returns False when no
        work remains.

        The whole round runs inside the sync sanitizer's hot scope
        (``lint/sanitizer.py hot_path``, armed by
        ``CRDT_BENCH_SANITIZE_SYNCS=1``): a host sync anywhere in here
        that is not behind a ``# graftlint: fence`` function raises at
        its exact callsite — the dynamic proof of the static G002
        model.  Unarmed, the scope is a no-op.

        The round is also the **hot thread root** of the
        thread-confinement model (lint/threads.py, G014-G016): every
        object it shares with the status threads crosses through the
        status server's declared publish points as an immutable
        snapshot swap — under ``CRDT_BENCH_SANITIZE_RACES=1`` an
        unpublished cross-thread access raises the same way an
        undeclared sync does."""
        with hot_path():
            if self.profiler is not None:
                self.profiler.round_begin()
            rt = self.reqtrace
            rt.round_begin()  # reset segment/hop accumulators (no-op
            # disarmed; armed, this round's phase timings and publish-
            # point entries fold into every scheduled doc's context)
            t0 = time.perf_counter()
            with span("serve.round", round=self.round):
                # adopt completed prefetches BEFORE planning: this
                # round's admissions see them as warm hits (no-op
                # without the tiered pool)
                self._harvest_prefetch()
                if self.faults is not None:
                    with span("serve.faults.inject"):
                        self._fire_overflow()
                        self._fire_tier_pressure()
                with span("serve.plan"), rt.segment("plan"):
                    plan = self._plan()
                if plan is None:
                    return False
                if self.reshard is not None \
                        and self.reshard.state != "done":
                    # placed plan in hand, WAL record not yet written:
                    # the coordinator's migrations join THIS round's
                    # boundary compose, and the journal sees the round
                    # only after every move decision is in it
                    with span("serve.reshard"):
                        self.reshard.tick(
                            plan.base_round, plan,
                            imbalance=self._shard_imbalance(),
                            note_deferred=self._note_reshard_deferred,
                        )
                if rt.armed:
                    # the lane set is final: publishes from here to the
                    # drain fence carry exactly these docs' data, so
                    # hop attribution (even for a mid-round close) is
                    # scoped to them
                    rt.note_scheduled(
                        l.stream.doc_id
                        for lanes in plan.lanes.values() for l in lanes
                    )
                if self.journal is not None:
                    # write-ahead: the lane set is durable BEFORE dispatch
                    with span("serve.journal.wal"), rt.segment("wal"):
                        self.journal.round_record(plan.base_round, {
                            cls: [[l.stream.doc_id, int(l.stream.cursor),
                                   int(l.end)]
                                  for l in lanes]
                            for cls, lanes in plan.lanes.items()
                        })
                with span("serve.stage"), rt.segment("stage"):
                    tensors = self._stage(plan)
                if self.faults is not None:
                    # its own segment: an injected stall must show up
                    # in request traces AS the stall, not as phantom
                    # inter-round queue wait
                    with rt.segment("faults"):
                        self._maybe_stall(plan.base_round)
                with span("serve.moves"), rt.segment("moves"):
                    self._execute_moves(plan)
                # submit the NEXT horizon's cold docs now: _select
                # already rotated the queue (deferred docs lead it), so
                # the front IS next round's admission order, and the
                # moves above just demoted this round's warm overflow —
                # the worker rehydrates while the dispatch below drains
                # on device (both GIL-releasing)
                self._plan_prefetch()
                if self.faults is not None:
                    with span("serve.faults.inject"):
                        self._fire_spool_fault(plan)
                with span("serve.dispatch"), rt.segment("dispatch"):
                    compiled = self._dispatch(plan, tensors)
                if rt.armed:
                    # fold BEFORE cursors advance (ops per lane still
                    # derivable) and before _advance's closes, so a
                    # request finishing this round carries this
                    # round's segments and hops
                    rt.fold_round(plan.base_round, [
                        (l.stream.doc_id, l.end - l.stream.cursor)
                        for lanes in plan.lanes.values() for l in lanes
                    ])
                self._advance(plan)
                if self._planned_degraded:
                    with span("serve.degraded_fence"):
                        self.pool.block()  # degraded mode: SYNCHRONOUS K=1
                self._maybe_snapshot()
            if self.telemetry is not None:
                # continuous telemetry: this round's sample (latency
                # here is pre-fence-fold — the time-series wants the
                # live rate; the artifact quantiles keep the folded
                # number via note_round).  Everything inside is pure
                # host arithmetic on pre-registered series (G013).
                self.telemetry.note_round(
                    round_no=self.round,
                    seconds=time.perf_counter() - t0,
                    compiled=compiled, barrier=self._snapped,
                    occupancy=self._last_occ,
                    queue_depth=self._last_queue,
                    cum=self._cum_counters(),
                    shard_lanes=self._sh_lanes, shard_ops=self._sh_ops,
                    shard_units=self._sh_units,
                    status=self.status_fields(),
                )
            # record the PREVIOUS round now and hold this one pending,
            # so run() can fold the final drain fence into the last
            # round's latency before it reaches the histogram
            self._flush_round()
            self._pending_round = (
                time.perf_counter() - t0, compiled, self._snapped
            )
            if self.reshard is not None:
                # mid-reshard tail visibility: rounds served while the
                # move is in flight feed the artifact's reshard block
                self.reshard.note_round_latency(
                    time.perf_counter() - t0
                )
            if self.profiler is not None:
                self.profiler.round_end(
                    steady=not compiled and not self._snapped
                )
            return True

    def _flush_round(self) -> None:
        """Commit the held round's latency through the single
        classification point (``ServeStats.note_round``)."""
        if self._pending_round is not None:
            self.stats.note_round(*self._pending_round)
            self._pending_round = None

    def run(self, max_rounds: int | None = None) -> ServeStats:
        """Drain every queue (or stop after ``max_rounds`` macro-rounds).
        Synchronization discipline: each run_round syncs only at its
        boundary moves; the device drains behind the host planner and is
        fenced once here at the end."""
        t0 = time.perf_counter()
        n = 0
        while self.run_round():
            n += 1
            if max_rounds is not None and n >= max_rounds:
                break
        tail0 = time.perf_counter()
        with span("serve.drain_fence"):
            self.pool.block()  # final fence: the last macro-round's drain
        if self._pending_round is not None:
            dt, c, b = self._pending_round
            self._pending_round = (
                dt + time.perf_counter() - tail0, c, b
            )
        self._flush_round()
        if self.reshard is not None and self.done:
            # BEFORE the fault sweep: a crashed coordinator resumes and
            # commits here, closing its reshard_crash event as a real
            # recovery — finalize_faults must never sweep it as merely
            # "terminal".  An interrupted run (crash round) skips this
            # and leaves the manifest for recover_fleet's roll-forward.
            with span("serve.reshard.finalize"):
                self.reshard.finalize(self.round)
        self._flush_drained_gc(force=True)
        if self.faults is not None and self.done:
            # gate on DONE, not on max_rounds: a --serve-crash-round
            # larger than the natural drain length completes the drain,
            # and a completed drain must always sweep its faults — only
            # a genuinely interrupted run leaves recovery to the
            # journal (the bench recovery leg closes its events there)
            with span("serve.finalize_faults"):
                self.finalize_faults()
        self.stats.wall_time += time.perf_counter() - t0
        self.stats.evictions = self.pool.evictions
        self.stats.restores = self.pool.restores
        self.stats.promotions = self.pool.promotions
        if self._lazy:
            # total patch count is only known once docs materialize:
            # at drain end the lazy tally IS the eager sum
            self.stats.patches = self.streams.patches_total
        return self.stats

    @property
    def done(self) -> bool:
        if self._lazy:
            return self.streams.all_done
        return all(s.remaining == 0 for s in self.streams.values())
