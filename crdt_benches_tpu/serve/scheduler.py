"""Admission + batching scheduler for the document fleet.

Drains per-doc op queues into fixed-shape device batches: every round,
each capacity class gets one (R, B) unit-op batch — row r carries the
next ≤B ops of the doc resident in row r, idle rows are padded with
``kind == PAD`` no-ops — and the pool applies it in one vmapped step.

Policy (deterministic, host-only — no device syncs on the decision path):

- **round-robin fairness**: active docs are served in FIFO order and
  rotate to the back after being scheduled, so a huge doc cannot starve
  the fleet;
- **class selection per chunk**: a doc's capacity need after its next
  chunk is host-known (n_init + cumulative inserts), so promotion to a
  larger class happens *before* the chunk that would overflow — the
  device never sees an over-capacity insert;
- **eviction**: when a selected doc's target bucket has no free row, the
  scheduler evicts a resident that is not scheduled this round —
  finished docs first, then least-recently-scheduled — through the
  pool's checkpoint spool.  A selected set never exceeds the bucket's
  row count, so a victim always exists.
- **arrival**: each doc becomes active at its session's arrival round
  (the workload's arrival staggering), modeling sessions joining a live
  server rather than a cold batch job.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ..traces.tensorize import INSERT, PAD, tensorize
from .pool import DocPool


@dataclass
class DocStream:
    """One doc's pending op queue (host-side, read-only arrays + cursor)."""

    doc_id: int
    kind: np.ndarray  # int32[N] unit ops (unpadded)
    pos: np.ndarray
    slot: np.ndarray
    ins_cum: np.ndarray  # int32[N] inclusive cumulative INSERT count
    n_patches: int
    arrival: int = 0
    cursor: int = 0

    @property
    def remaining(self) -> int:
        return len(self.kind) - self.cursor

    def need_after(self, n_init: int, take: int) -> int:
        """Slot capacity needed once the next ``take`` ops are applied."""
        end = self.cursor + take
        return n_init + (int(self.ins_cum[end - 1]) if end else 0)


def prepare_streams(sessions, pool: DocPool, batch: int = 64
                    ) -> dict[int, DocStream]:
    """Tensorize every session's trace, register the docs with the pool,
    and return the per-doc op queues.  Sessions sharing an identical
    trace object (the workload caches trace prefixes) share the
    tensorized arrays — the queues only differ in cursor state."""
    streams: dict[int, DocStream] = {}
    cache: dict[int, tuple] = {}  # id(trace) -> (tt, chars)
    for s in sessions:
        hit = cache.get(id(s.trace))
        if hit is None:
            tt = tensorize(s.trace, batch=1)
            chars = np.zeros(tt.capacity, np.int32)
            chars[: len(tt.init_chars)] = tt.init_chars
            ins = tt.kind == INSERT
            chars[tt.slot[ins]] = tt.ch[ins]
            hit = cache[id(s.trace)] = (tt, chars)
        tt, chars = hit
        n = tt.n_ops
        pool.register(
            s.doc_id, n_init=len(tt.init_chars),
            capacity_need=tt.capacity, chars=chars,
        )
        streams[s.doc_id] = DocStream(
            doc_id=s.doc_id,
            kind=tt.kind[:n], pos=tt.pos[:n], slot=tt.slot[:n],
            ins_cum=np.cumsum(tt.kind[:n] == INSERT).astype(np.int32),
            n_patches=tt.n_patches,
            arrival=getattr(s, "arrival", 0),
        )
    return streams


@dataclass
class ServeStats:
    """One drain's telemetry (the serve family's report surface)."""

    round_latencies: list[float] = field(default_factory=list)
    occupancy: list[float] = field(default_factory=list)  # per round
    queue_depth: list[int] = field(default_factory=list)  # per round
    rounds: int = 0
    ops: int = 0
    patches: int = 0
    evictions: int = 0
    restores: int = 0
    promotions: int = 0
    admissions: int = 0
    wall_time: float = 0.0


class FleetScheduler:
    def __init__(self, pool: DocPool, streams: dict[int, DocStream],
                 batch: int = 64):
        self.pool = pool
        self.streams = streams
        self.batch = batch
        self.round = 0
        # FIFO of doc ids not yet arrived or with pending ops, in
        # arrival order (stable for determinism).
        self._rr = deque(sorted(
            streams, key=lambda d: (streams[d].arrival, d)
        ))
        self.stats = ServeStats(
            patches=sum(s.n_patches for s in streams.values())
        )

    # ---- one round ----

    def _select(self) -> tuple[dict[int, list], int]:
        """Pick this round's lanes: {class: [(stream, take)]}, bounded by
        each bucket's row count, in round-robin order.  Returns the plan
        and the number of active docs left waiting (queue depth)."""
        plan: dict[int, list] = {c: [] for c in self.pool.classes}
        waiting = 0
        scheduled: list[int] = []
        deferred: list[int] = []
        while self._rr:
            doc_id = self._rr.popleft()
            st = self.streams[doc_id]
            if st.remaining == 0:
                continue  # drained: drop from the rotation for good
            if st.arrival > self.round:
                deferred.append(doc_id)
                continue
            take = min(self.batch, st.remaining)
            rec = self.pool.docs[doc_id]
            cls = self.pool.class_for(
                max(st.need_after(rec.n_init, take), rec.length, 1)
            )
            b = self.pool.buckets[cls]
            if len(plan[cls]) >= b.R:
                waiting += 1
                deferred.append(doc_id)
                continue
            plan[cls].append((st, take))
            scheduled.append(doc_id)
        # rotation: scheduled docs go to the back; deferred keep order.
        self._rr.extend(deferred)
        self._rr.extend(scheduled)
        return plan, waiting

    def _place(self, cls: int, lanes: list, selected_all: set[int]) -> None:
        """Make every selected doc resident in ``cls``, evicting
        not-selected residents when the bucket is full."""
        selected = {st.doc_id for st, _ in lanes}
        b = self.pool.buckets[cls]
        for st, take in lanes:
            rec = self.pool.docs[st.doc_id]
            if rec.cls == cls:
                continue
            if not b.free:
                victim = self._pick_victim(cls, selected, selected_all)
                self.pool.evict(victim)
            self.pool.admit(st.doc_id, st.need_after(rec.n_init, take))
            self.stats.admissions += 1

    def _pick_victim(self, cls: int, selected: set[int],
                     selected_all: set[int]) -> int:
        """Eviction victim in ``cls``: finished docs first, then the
        least recently scheduled pending doc not selected this round.
        Docs scheduled in ANY class this round (e.g. a resident about to
        promote out of ``cls``) are spared when possible — evicting one
        would turn its direct promotion into a spool round-trip — but
        remain the liveness fallback: only this class's own selected set
        is guaranteed to leave a candidate."""
        candidates = [
            d for d, _row in self.pool.residents(cls) if d not in selected
        ]
        if not candidates:
            raise RuntimeError(
                f"bucket c{cls}: no eviction candidate "
                "(selected set exceeds bucket rows?)"
            )
        preferred = [d for d in candidates if d not in selected_all]
        return min(
            preferred or candidates,
            key=lambda d: (
                self.streams[d].remaining > 0,  # finished docs first
                self.pool.docs[d].last_sched,
                d,
            ),
        )

    def run_round(self) -> bool:
        """One scheduling round.  Returns False when no work remains."""
        plan, waiting = self._select()
        lanes_used = sum(len(v) for v in plan.values())
        if lanes_used == 0:
            if any(
                s.remaining and s.arrival > self.round
                for s in self.streams.values()
            ):
                self.round += 1  # idle tick: waiting on arrivals
                return True
            return False
        selected_all = {
            st.doc_id for lanes in plan.values() for st, _ in lanes
        }
        t0 = time.perf_counter()
        for cls, lanes in plan.items():
            if not lanes:
                continue
            self._place(cls, lanes, selected_all)
            b = self.pool.buckets[cls]
            B = self.batch
            kind = np.full((b.R, B), PAD, np.int32)
            pos = np.zeros((b.R, B), np.int32)
            slot = np.full((b.R, B), -1, np.int32)
            for st, take in lanes:
                rec = self.pool.docs[st.doc_id]
                r, c0 = rec.row, st.cursor
                kind[r, :take] = st.kind[c0:c0 + take]
                pos[r, :take] = st.pos[c0:c0 + take]
                slot[r, :take] = st.slot[c0:c0 + take]
            self.pool.step(cls, kind, pos, slot)
            for st, take in lanes:
                rec = self.pool.docs[st.doc_id]
                st.cursor += take
                rec.length = rec.n_init + int(st.ins_cum[st.cursor - 1])
                rec.last_sched = self.round
                self.stats.ops += take
        self.pool.block()
        dt = time.perf_counter() - t0
        self.stats.round_latencies.append(dt)
        total_lanes = sum(b.R for b in self.pool.buckets.values())
        self.stats.occupancy.append(lanes_used / total_lanes)
        self.stats.queue_depth.append(waiting)
        self.round += 1
        return True

    def run(self, max_rounds: int | None = None) -> ServeStats:
        """Drain every queue (or stop after ``max_rounds``)."""
        t0 = time.perf_counter()
        n = 0
        while self.run_round():
            n += 1
            if max_rounds is not None and n >= max_rounds:
                break
        self.stats.wall_time += time.perf_counter() - t0
        self.stats.rounds = len(self.stats.round_latencies)
        self.stats.evictions = self.pool.evictions
        self.stats.restores = self.pool.restores
        self.stats.promotions = self.pool.promotions
        return self.stats

    @property
    def done(self) -> bool:
        return all(s.remaining == 0 for s in self.streams.values())
