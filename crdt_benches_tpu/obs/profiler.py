"""Device-profiler capture of steady serve rounds (``--serve-profile``).

``tools/profile.py trace`` exists for ad-hoc kernel digs; this module
makes the same capability a *bench artifact feature*: ask the serve
bench for ``--serve-profile N`` and it records a ``jax.profiler``
device trace spanning N **steady** macro-rounds — compile rounds and
snapshot-barrier rounds are excluded by the same round classification
that feeds the latency histograms (``ServeStats.note_round``), so the
trace shows serving work, not XLA compilation or barrier I/O — then
parses the trace and embeds a top-ops summary table in the artifact's
``profile`` block.

The profiler is a tiny state machine driven by two scheduler hooks:

- ``round_begin()`` — called at the top of every macro-round; starts
  the capture once at least one steady round has been observed (so the
  hot shapes are compiled before the window opens);
- ``round_end(steady)`` — counts steady rounds inside the window and
  closes it after N.

``finalize(fence)`` stops a still-open capture (``fence`` drains the
device first so the trace holds completed work) and returns the
summary dict, or None when nothing was captured.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import shutil
import tempfile
from collections import defaultdict


class DeviceProfiler:
    """Capture N steady macro-rounds with ``jax.profiler``."""

    def __init__(self, n_rounds: int, logdir: str | None = None):
        self.n_rounds = max(1, int(n_rounds))
        self._owns_dir = logdir is None
        self.logdir = logdir or tempfile.mkdtemp(prefix="crdt_profile_")
        self.state = "wait"  # wait -> ready -> on -> done
        self.captured = 0
        self.dirty_rounds = 0  # non-steady rounds inside the window
        self.summary: dict | None = None

    # ---- scheduler hooks ----

    def round_begin(self) -> None:
        if self.state != "ready":
            return
        import jax

        jax.profiler.start_trace(self.logdir)
        self.state = "on"

    def round_end(self, steady: bool) -> None:
        if self.state == "wait":
            if steady:
                self.state = "ready"  # hot shapes compiled: open next round
            return
        if self.state == "on":
            if steady:
                self.captured += 1
                if self.captured >= self.n_rounds:
                    self._stop()
            else:
                # a late compile / snapshot barrier landed inside the
                # window — surfaced in the summary, not hidden
                self.dirty_rounds += 1

    # ---- capture lifecycle ----

    def _stop(self) -> None:
        import jax

        jax.profiler.stop_trace()
        self.state = "done"

    def finalize(self, fence=None) -> dict | None:
        """Close an open capture (fencing the device first so in-flight
        dispatches land in the trace), parse it, and return the
        ``profile`` artifact block.  Idempotent, and safe on a crashed
        drain: a failing fence must not leave the capture open (a
        dangling ``start_trace`` poisons every later profile in the
        process)."""
        if self.state == "on":
            try:
                if fence is not None:
                    fence()
            finally:
                self._stop()
        if self.state != "done":
            self._cleanup()
            return None
        if self.summary is None:
            self.summary = {
                "rounds": self.captured,
                "requested": self.n_rounds,
                "dirty_rounds": self.dirty_rounds,
                "top_ops": top_ops(self.logdir),
            }
            if not self._owns_dir:
                self.summary["logdir"] = self.logdir
            self._cleanup()
        return self.summary

    def _cleanup(self) -> None:
        if self._owns_dir:
            shutil.rmtree(self.logdir, ignore_errors=True)


def top_ops(logdir: str, limit: int = 15) -> list[dict]:
    """Aggregate the complete ("X") events of every trace file under
    ``logdir`` into a top-ops table: total self-reported duration and
    call count per op name, heaviest first (the same digest
    ``tools/profile.py trace`` prints, in artifact form)."""
    agg: dict[str, float] = defaultdict(float)
    cnt: dict[str, int] = defaultdict(int)
    for path in glob.glob(
        os.path.join(logdir, "**", "*.trace.json.gz"), recursive=True
    ):
        try:
            with gzip.open(path, "rt", encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            continue
        for ev in data.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            name = ev.get("name", "")
            dur_ms = ev.get("dur", 0) / 1e3
            if not name or dur_ms <= 0:
                continue
            # drop the profiler's host-side Python-frame events
            # ("$scheduler.py:1231 run_round") — the table is about
            # device/XLA op cost, not the Python call stack
            if ".py:" in name or name.startswith("$"):
                continue
            agg[name] += dur_ms
            cnt[name] += 1
    return [
        {"name": name[:160], "total_ms": round(ms, 3), "calls": cnt[name]}
        for name, ms in sorted(agg.items(), key=lambda kv: -kv[1])[:limit]
    ]
