"""Unified observability for the serve/bench stack.

Three cooperating pieces, all zero-cost when disarmed:

- :mod:`crdt_benches_tpu.obs.trace` — a phase-span tracer for the
  macro-round lifecycle.  ``with span("serve.plan"):`` compiles to a
  shared no-op context manager unless armed (``--serve-trace`` /
  ``CRDT_BENCH_TRACE=1``); armed, it records Chrome trace-event JSON
  loadable in Perfetto, with every ``@fenced`` boundary crossing from
  ``lint/sanitizer.py`` emitted as an instant event inside its owning
  span — the G011 fence model and the timeline are one picture.
- :mod:`crdt_benches_tpu.obs.metrics` — a typed metric registry
  (Counter / Gauge / fixed-bucket mergeable Histogram) that backs
  ``ServeStats``: per-round latency/occupancy/queue-depth live in
  O(buckets) histograms instead of unbounded Python lists, and the
  serve artifact carries the whole registry as a versioned ``metrics``
  block.
- :mod:`crdt_benches_tpu.obs.profiler` — ``--serve-profile N`` captures
  a ``jax.profiler`` device trace of N steady (non-compile,
  non-barrier) macro-rounds and writes a top-ops summary into the
  artifact.

``tools/bench_compare.py`` closes the loop: it diffs a fresh serve
artifact against the committed baseline (throughput, steady p99,
journal overhead, boundary syncs) with noise thresholds, so the
BENCH_r* trajectory is an enforced contract.
"""
