"""Unified observability for the serve/bench stack.

Three cooperating pieces, all zero-cost when disarmed:

- :mod:`crdt_benches_tpu.obs.trace` — a phase-span tracer for the
  macro-round lifecycle.  ``with span("serve.plan"):`` compiles to a
  shared no-op context manager unless armed (``--serve-trace`` /
  ``CRDT_BENCH_TRACE=1``); armed, it records Chrome trace-event JSON
  loadable in Perfetto, with every ``@fenced`` boundary crossing from
  ``lint/sanitizer.py`` emitted as an instant event inside its owning
  span — the G011 fence model and the timeline are one picture.
- :mod:`crdt_benches_tpu.obs.metrics` — a typed metric registry
  (Counter / Gauge / fixed-bucket mergeable Histogram) that backs
  ``ServeStats``: per-round latency/occupancy/queue-depth live in
  O(buckets) histograms instead of unbounded Python lists, and the
  serve artifact carries the whole registry as a versioned ``metrics``
  block.
- :mod:`crdt_benches_tpu.obs.profiler` — ``--serve-profile N`` captures
  a ``jax.profiler`` device trace of N steady (non-compile,
  non-barrier) macro-rounds and writes a top-ops summary into the
  artifact.

obs/ v2 adds the *continuous* layer (all disarmed by default, armed by
``--serve-status`` / ``--serve-timeseries`` / ``--serve-soak``):

- :mod:`crdt_benches_tpu.obs.timeseries` — a ring-buffered windowed
  recorder folding per-round samples into delta-encoded windows (the
  versioned ``timeseries`` artifact block + an optional live JSONL
  stream) and the ``ServeTelemetry`` facade the scheduler threads
  through the drain;
- :mod:`crdt_benches_tpu.obs.shard` — mesh-aware per-shard series
  (ops/lanes/occupancy/relocations, an imbalance gauge, device
  allocator stats) whose per-shard sums equal the fleet totals;
- :mod:`crdt_benches_tpu.obs.status` — a thread-confined stdlib HTTP
  status server (``/healthz``, ``/status.json``, ``/metrics`` in
  Prometheus text exposition) read-only over published snapshots,
  plus a ``--watch`` polling CLI;
- :mod:`crdt_benches_tpu.obs.anomaly` — online soak detectors
  (throughput degradation, RSS/journal leak growth, a stuck-round
  watchdog) landing in the ``anomalies`` artifact block and the run's
  exit code.

``tools/bench_compare.py`` closes the loop: it diffs a fresh serve
artifact against the committed baseline (throughput, steady p99,
journal overhead, boundary syncs, and — when both sides carry
time-series — the worst full window's throughput floor) with noise
thresholds, so the BENCH_r* trajectory is an enforced contract.
"""
