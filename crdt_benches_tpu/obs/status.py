"""Live serve status: a thread-confined stdlib HTTP endpoint.

A drain used to be a black box until its artifact landed; this module
makes the run observable WHILE it serves.  ``--serve-status PORT``
starts :class:`StatusServer` — a ``ThreadingHTTPServer`` on its own
daemon thread — serving three read-only endpoints:

- ``/healthz`` — liveness + health: 200 when the drain is publishing
  and no anomaly is active, 503 (with the reason) otherwise, including
  when the publisher has gone silent past ``stale_after`` seconds — an
  external probe sees a wedged host even when the process is alive;
- ``/status.json`` — the latest per-round snapshot (current round,
  occupancy, queue depth, shed/deferred/quarantine totals, degraded
  and fault state), fields advancing monotonically through the drain;
- ``/metrics`` — the drain's full typed-metric registry rendered in
  Prometheus text exposition format (``# HELP`` / ``# TYPE``, counters
  as ``_total``, histograms as cumulative ``_bucket``/``_sum``/
  ``_count``, registry keys like ``serve.shard.ops{shard="3"}`` parsed
  into real label sets with proper value escaping).

Isolation contract (enforced by graftlint G013): the serving hot path
never constructs sockets, never renders, never mutates the registry —
it only swaps immutable snapshot references in via
:meth:`StatusServer.publish_status` / :meth:`publish_metrics` (one
attribute store each; CPython makes the reference swap atomic).  All
socket work and rendering happens on the server's own threads against
whatever snapshot is current.

Concurrency contract (enforced by graftlint G014/G015 + the runtime
race sanitizer, lint/threads.py + lint/race_sanitizer.py): the
publisher methods are owned by the **hot** thread, the handler surface
by the **status** threads, and the ONLY mutable state crossing between
them — the status and metrics snapshots — crosses inside the two
declared ``# graftlint: publish`` points below, as an atomic reference
swap of an object the publisher never touches again.  Health is a
single immutable ``(ok, reason)`` tuple swap for the same reason (two
separate field stores could be observed torn).  Under
``CRDT_BENCH_SANITIZE_RACES=1`` the snapshots become ownership-tracking
proxies and any unpublished cross-thread access raises at its
callsite; the per-point publish/crossing counters land in the serve
artifact's ``thread_crossings`` block, which lint rule G017
cross-checks against these annotations.

A polling terminal view ships as the module CLI::

    python -m crdt_benches_tpu.obs.status --watch --url http://127.0.0.1:8787
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from threading import Thread

from ..lint.race_sanitizer import published, reveal, share

# ---------------------------------------------------------------------------
# Prometheus text exposition (format version 0.0.4)
# ---------------------------------------------------------------------------

_LABELED_RE = re.compile(r"^(?P<base>[^{]+)(?:\{(?P<labels>.*)\})?$")
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')
_NAME_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_:]")

CONTENT_TYPE_LATEST = "text/plain; version=0.0.4; charset=utf-8"


def split_labeled_name(name: str) -> tuple[str, dict[str, str]]:
    """``'serve.shard.ops{shard="3"}'`` -> (``serve.shard.ops``,
    ``{"shard": "3"}``).  Unlabeled names return an empty dict."""
    m = _LABELED_RE.match(name)
    if m is None:
        return name, {}
    labels = dict(_LABEL_PAIR_RE.findall(m.group("labels") or ""))
    return m.group("base"), labels


def prom_name(base: str) -> str:
    """A registry base name as a valid Prometheus metric name."""
    out = _NAME_SANITIZE_RE.sub("_", base)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def escape_label_value(v: str) -> str:
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _label_str(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _num(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return format(f, ".10g")


def render_prometheus(metrics: dict) -> str:
    """Render a ``MetricsRegistry.to_dict()`` snapshot as Prometheus
    text exposition.  Same-base labeled series share one ``# HELP`` /
    ``# TYPE`` header; counters gain the ``_total`` suffix; histograms
    emit cumulative ``_bucket`` lines (``le`` merged into the series'
    own labels), ``_sum`` and ``_count``."""
    lines: list[str] = []

    def _grouped(table: dict) -> dict[str, list[tuple[dict, object]]]:
        groups: dict[str, list[tuple[dict, object]]] = {}
        for name in sorted(table):
            base, labels = split_labeled_name(name)
            groups.setdefault(base, []).append((labels, table[name]))
        return groups

    for base, series in _grouped(metrics.get("counters", {})).items():
        n = prom_name(base) + "_total"
        lines.append(f"# HELP {n} registry counter {base}")
        lines.append(f"# TYPE {n} counter")
        for labels, value in series:
            lines.append(f"{n}{_label_str(labels)} {_num(value)}")
    for base, series in _grouped(metrics.get("gauges", {})).items():
        n = prom_name(base)
        lines.append(f"# HELP {n} registry gauge {base}")
        lines.append(f"# TYPE {n} gauge")
        for labels, g in series:
            lines.append(f"{n}{_label_str(labels)} {_num(g['value'])}")
    for base, series in _grouped(metrics.get("histograms", {})).items():
        n = prom_name(base)
        lines.append(f"# HELP {n} registry histogram {base}")
        lines.append(f"# TYPE {n} histogram")
        for labels, h in series:
            cum = 0
            for bound, c in zip(h["bounds"], h["counts"]):
                cum += c
                bl = dict(labels, le=_num(bound))
                lines.append(f"{n}_bucket{_label_str(bl)} {cum}")
            bl = dict(labels, le="+Inf")
            lines.append(f"{n}_bucket{_label_str(bl)} {h['count']}")
            ls = _label_str(labels)
            lines.append(f"{n}_sum{ls} {_num(h['sum'])}")
            lines.append(f"{n}_count{ls} {h['count']}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# the status server
# ---------------------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):  # graftlint: thread=status
    server_version = "crdt-serve-status/1"

    def log_message(self, *args) -> None:  # no stderr chatter per scrape
        pass

    def _reply(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler API)
        owner: StatusServer = self.server.owner  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            ok, reason = owner.health()
            body = json.dumps({"ok": ok, "reason": reason}).encode()
            self._reply(200 if ok else 503, body, "application/json")
        elif path == "/status.json":
            body = json.dumps(owner.status_snapshot()).encode()
            self._reply(200, body, "application/json")
        elif path == "/metrics":
            body = render_prometheus(owner.metrics_snapshot()).encode()
            self._reply(200, body, CONTENT_TYPE_LATEST)
        else:
            self._reply(
                404,
                b'{"error": "unknown path", '
                b'"endpoints": ["/healthz", "/status.json", "/metrics"]}',
                "application/json",
            )


class StatusServer:
    """Read-only HTTP view over published snapshots.

    The publisher (the drain) calls :meth:`publish_status` /
    :meth:`publish_metrics` with plain dicts it will not mutate again;
    the handler threads only ever read the current reference.  Health
    combines the published verdict with a staleness check
    (``stale_after`` seconds without a publish -> 503)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 stale_after: float | None = None):
        self._host = host
        self._want_port = int(port)
        self.stale_after = stale_after
        self._status: dict = {}
        self._metrics: dict = {}
        # ONE immutable tuple, swapped atomically: a reader that raced
        # two separate ok/reason stores could pair a new verdict with a
        # stale reason (found by the G014/G015 audit, ISSUE 10)
        self._health: tuple[bool, str] = (True, "")
        self._last_publish = time.monotonic()
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: Thread | None = None

    # ---- lifecycle (driver side only; G013 bans this in hot scopes) --

    def start(self) -> int:
        httpd = ThreadingHTTPServer((self._host, self._want_port), _Handler)
        httpd.daemon_threads = True
        httpd.owner = self  # type: ignore[attr-defined]
        self._httpd = httpd
        self._thread = Thread(
            target=httpd.serve_forever, name="serve-status", daemon=True
        )
        self._thread.start()
        return self.port

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else 0

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # ---- publisher side (hot path: reference swaps only) ----

    @published
    def publish_status(self, snapshot: dict) -> None:  # graftlint: publish=status  # graftlint: thread=hot
        snapshot["ts"] = time.time()
        self._status = share(snapshot, "StatusServer.status")
        self._last_publish = time.monotonic()

    @published
    def publish_metrics(self, metrics: dict) -> None:  # graftlint: publish=status  # graftlint: thread=hot
        self._metrics = share(metrics, "StatusServer.metrics")

    def set_health(self, ok: bool, reason: str = "") -> None:  # graftlint: thread=hot
        self._health = (ok, reason)  # immutable tuple: atomic swap

    # ---- reader side (handler threads) ----

    def status_snapshot(self) -> dict:  # graftlint: thread=status
        return reveal(self._status)

    def metrics_snapshot(self) -> dict:  # graftlint: thread=status
        return reveal(self._metrics)

    def health(self) -> tuple[bool, str]:  # graftlint: thread=status
        if self.stale_after is not None:
            silent = time.monotonic() - self._last_publish
            if silent > self.stale_after:
                return False, f"stale: no publish for {silent:.1f}s"
        ok, reason = self._health
        if not ok:
            return False, reason or "anomaly active"
        return True, ""


# ---------------------------------------------------------------------------
# polling terminal view
# ---------------------------------------------------------------------------


def _fetch_json(url: str, timeout: float = 2.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.load(r)


def watch(url: str, interval: float = 1.0, count: int | None = None,
          out=None) -> int:
    """Poll ``URL/status.json`` and print one line per sample.  Returns
    0; a scrape error prints and retries (the run may still be coming
    up) unless ``count`` is exhausted."""
    out = out or sys.stdout
    seen = 0
    while count is None or seen < count:
        try:
            s = _fetch_json(url.rstrip("/") + "/status.json")
        except (OSError, ValueError) as e:  # conn refused, cut body, ...
            print(f"watch: {url}: {e}", file=out)
        else:
            anomalies = s.get("anomalies_active") or []
            print(
                f"round {s.get('round', '?'):>6}  "
                f"rounds {s.get('rounds', '?'):>5}  "
                f"occ {s.get('occupancy', 0.0):.2f}  "
                f"queue {s.get('queue_depth', 0):>4}  "
                f"ops {s.get('ops', 0):>8}  "
                f"shed {s.get('shed_ops', 0)}  "
                f"deferred {s.get('deferred_ops', 0)}  "
                f"degraded {int(bool(s.get('degraded')))}  "
                + (f"ANOMALY[{','.join(anomalies)}]" if anomalies
                   else "healthy"),
                file=out,
            )
        seen += 1
        if count is None or seen < count:
            time.sleep(interval)
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m crdt_benches_tpu.obs.status",
        description="poll a live serve drain's status endpoint",
    )
    ap.add_argument("--watch", action="store_true",
                    help="poll /status.json and print one line per "
                         "sample (the only mode; flag kept explicit)")
    ap.add_argument("--url", default=None,
                    help="status server base URL "
                         "(default http://127.0.0.1:PORT)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8787)
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--count", type=int, default=None,
                    help="stop after N samples (default: forever)")
    args = ap.parse_args(argv)
    url = args.url or f"http://{args.host}:{args.port}"
    return watch(url, interval=args.interval, count=args.count)


if __name__ == "__main__":
    sys.exit(main())
