"""Request-scoped causal tracing: where each doc request's time went.

PR 6/7 telemetry sees *rounds*; an SLO-aware admission scheduler needs
to see *requests*: one *request* = one admission-to-drain episode of one
document — opened when the FleetScheduler first schedules the doc,
closed when its stream ends (drained / shed / quarantined).  The
:class:`RequestTracker` owns that lifecycle:

- **context** — doc id, request id, episode number (a doc re-admitted
  after a close opens a FRESH context: two episodes are two requests,
  each counted once — the PR 6 ``_admit_t`` scheme keyed timestamps by
  doc identity, which double-counted a re-admitted doc under one
  identity), admission round/wall time, and its **latency budget
  class** (``obs/slo.py`` classification of the admission capacity
  class);
- **segments** — per-request time breakdown folded once per macro-round
  from the scheduler's phase timings (``plan`` / ``wal`` / ``stage`` /
  ``moves`` / ``dispatch``), plus ``queue`` (inter-round wait the
  phases do not cover) and ``drain`` (close-time residual tail).
  Disarmed, :meth:`segment` returns one shared no-op context manager —
  the same zero-cost contract as ``obs/trace.py span``;
- **publish-point hops** — every declared ``# graftlint: publish``
  entry (``lint/race_sanitizer.py``) observed during a round is folded
  into the round's active contexts, so a request trace records exactly
  which cross-thread propagation edges its data rode (status snapshot,
  journal WAL record, broadcast-bus block).  The race sanitizer's
  publish counters and the request trace are one causal picture: a
  sampled trace's hop set is always a subset of the artifact's
  ``thread_crossings`` publishes (cross-checked in the bench smoke);
- **exemplars** — at close, the request is attached to the
  ``doc_drain_latency`` histogram bucket its latency lands in (last
  request per bucket wins), so a p99.9 outlier in the artifact links
  to the exact request's segment breakdown;
- **remote-merge attribution** — on a replicated fleet, the remote ops
  a replica merges are attributed to their ORIGINATING writer
  (``remote_ops`` keyed by writer index).

Discipline (enforced by graftlint G012/G013): contexts are opened and
exemplars sampled at admission/drain EDGES — never in per-op inner
loops — and the tracker/flight lifecycle (construction, arming) belongs
to the bench driver, not the hot path.

Thread confinement: the tracker is owned by the **hot** thread.  The
publish observer only ever fires from publisher-side entries (which run
on the hot thread); readers see request data through the status
server's published snapshots, never the tracker.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import deque

from .trace import NOOP_SPAN

#: Bump when the ``reqtrace`` artifact block changes shape.
REQTRACE_VERSION = 1

#: The fixed per-request segment vocabulary.  ``queue`` and ``drain``
#: are derived (inter-round wait / close-time tail); ``faults`` is
#: injected stall time (so a chaos post-mortem points at the stall,
#: not at phantom queuing); the rest mirror the macro-round phases the
#: scheduler times.
SEGMENTS = ("queue", "plan", "wal", "stage", "moves", "dispatch",
            "faults", "drain")

#: Default sampled-trace ring size when armed without an explicit cap.
DEFAULT_SAMPLES = 16


#: The disarmed segment IS the disarmed span — one shared no-op
#: context manager across obs/, so the two identity contracts cannot
#: drift apart.
NOOP_SEGMENT = NOOP_SPAN


class _Segment:
    """One armed phase timing: accumulates into the tracker's
    per-round segment table on exit."""

    __slots__ = ("_tracker", "_name", "_t0")

    def __init__(self, tracker: "RequestTracker", name: str):
        self._tracker = tracker
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        segs = self._tracker._round_segs
        segs[self._name] = segs.get(self._name, 0.0) + (
            time.perf_counter() - self._t0
        )
        return False


class RequestContext:
    """One admission-to-drain episode of one document."""

    __slots__ = ("doc_id", "request_id", "episode", "budget_class",
                 "admit_round", "admit_t", "last_t", "rounds", "ops",
                 "segments", "hops", "remote_ops", "cause", "latency",
                 "close_round")

    def __init__(self, doc_id: int, request_id: int, episode: int,
                 budget_class: str, admit_round: int):
        self.doc_id = doc_id
        self.request_id = request_id
        self.episode = episode
        self.budget_class = budget_class
        self.admit_round = admit_round
        self.admit_t = time.perf_counter()
        self.last_t = self.admit_t
        self.rounds = 0
        self.ops = 0
        self.segments: dict[str, float] = {}
        self.hops: set[str] = set()
        self.remote_ops: dict[int, int] = {}
        self.cause: str | None = None
        self.latency: float | None = None
        self.close_round: int | None = None

    def to_dict(self) -> dict:
        return {
            "request": self.request_id,
            "doc": self.doc_id,
            "episode": self.episode,
            "class": self.budget_class,
            "admit_round": self.admit_round,
            "close_round": self.close_round,
            "cause": self.cause,
            "latency_s": self.latency,
            "rounds": self.rounds,
            "ops": self.ops,
            "segments": {k: self.segments[k] for k in sorted(self.segments)},
            "hops": sorted(self.hops),
            "remote_ops": {
                str(w): n for w, n in sorted(self.remote_ops.items())
            },
        }


class RequestTracker:  # graftlint: thread=hot
    """Request lifecycle owner (module docstring has the model).

    Disarmed (``samples=0`` and no SLO tracker — the default every
    plain drain gets), the tracker is exactly the PR 6 admission-
    timestamp table: ``open_request`` stores one float, ``close_request``
    pops it, :meth:`segment` is the shared no-op — identity asserted by
    tests.  Armed, every open creates a full :class:`RequestContext`
    and the publish observer is installed.
    """

    def __init__(self, samples: int = 0, slo=None):
        self.samples_cap = int(samples)
        self.slo = slo  # obs/slo.py SloTracker (or None)
        self.armed = self.samples_cap > 0 or slo is not None
        if self.armed and self.samples_cap <= 0:
            self.samples_cap = DEFAULT_SAMPLES
        # disarmed: the bare admission-timestamp table
        self._t0: dict[int, float] = {}
        # armed state
        self._active: dict[int, RequestContext] = {}
        self._episodes: dict[int, int] = {}
        self._samples: deque[RequestContext] = deque(
            maxlen=max(1, self.samples_cap)
        )
        self._round_segs: dict[str, float] = {}
        self._round_hops: set[str] = set()
        self._round_docs: set[int] = set()
        self.hop_counts: dict[str, int] = {}
        self.exemplars: dict[str, dict[int, dict]] = {}
        self._bounds: dict[str, tuple] = {}
        self.requests_opened = 0
        self.requests_closed = 0
        self.reopened = 0  # episodes > 1: fresh contexts on re-admission
        self._next_id = 0
        self._installed = False
        # the tracker's owning (hot) thread: the publish observer fires
        # on the PUBLISHING thread, and since the prefetch thread
        # gained its own declared publish point (serve/prefetch.py),
        # not every entry is hot-side anymore — see _on_publish
        self._owner = threading.get_ident()
        if self.armed:
            from ..lint import race_sanitizer

            race_sanitizer.add_publish_observer(self._on_publish)
            self._installed = True

    # ---- driver-side lifecycle ----

    def bind(self, stats) -> None:
        """Adopt the drain's cause-tagged drain-latency histograms as
        the exemplar target (their bounds define the buckets)."""
        if not self.armed:
            return
        self._bounds = {
            tag: h.bounds for tag, h in stats.doc_latency.items()
        }

    def release(self) -> None:
        """Remove the publish observer (each bench run owns its
        window).  Idempotent."""
        if self._installed:
            from ..lint import race_sanitizer

            race_sanitizer.remove_publish_observer(self._on_publish)
            self._installed = False

    # ---- the publish-hop observer (fires on the publishing thread,
    # which for every declared point in this stack is the hot thread) --

    def _on_publish(self, point: str) -> None:
        if threading.get_ident() != self._owner:
            # a publisher-side entry from ANOTHER thread (the prefetch
            # worker's result swap): by definition not part of any
            # request's causal path — prefetch runs BEFORE admission
            # opens a context — and folding it here would mutate
            # hot-owned accumulators cross-thread.  Dropped by design;
            # the race sanitizer's own counters still record the entry.
            return
        self._round_hops.add(point)
        self.hop_counts[point] = self.hop_counts.get(point, 0) + 1

    # ---- admission / close edges ----

    def open_request(self, doc_id: int, round_no: int,
                     cap_cls: int | None = None) -> None:
        """Open a request at admission — a no-op while one is already
        active for the doc.  A doc whose previous request CLOSED
        (drained / shed / quarantined) and that is scheduled again gets
        a FRESH context with a new request id and episode number: the
        two episodes are two requests, never one double-counted doc."""
        if not self.armed:
            if doc_id not in self._t0:
                self._t0[doc_id] = time.perf_counter()
            return
        if doc_id in self._active:
            return
        ep = self._episodes.get(doc_id, 0) + 1
        self._episodes[doc_id] = ep
        if ep > 1:
            self.reopened += 1
        budget = (
            self.slo.classify(cap_cls) if self.slo is not None
            else (f"c{cap_cls}" if cap_cls is not None else "default")
        )
        self._active[doc_id] = RequestContext(
            doc_id, self._next_id, ep, budget, round_no
        )
        self._next_id += 1
        self.requests_opened += 1

    def close_request(self, doc_id: int, cause: str,
                      round_no: int | None = None) -> float | None:
        """Close the doc's active request under its cause tag.  Returns
        the admission-to-drain latency in seconds, or None when no
        request is open (never admitted, or already closed — the first
        close wins, exactly once per episode)."""
        now = time.perf_counter()
        if not self.armed:
            t0 = self._t0.pop(doc_id, None)
            return None if t0 is None else now - t0
        ctx = self._active.pop(doc_id, None)
        if ctx is None:
            return None
        if doc_id in self._round_docs:
            # closed mid-round AFTER riding this round's publishes (a
            # scheduled doc quarantined post-WAL): its lane was in the
            # journaled set, so the round's hops are its hops.  A doc
            # closed while NOT in this round's lane set (deferred off a
            # lost shard, drained at selection) must not be stamped
            # with edges its data never rode.
            ctx.hops |= self._round_hops
        ctx.cause = cause
        ctx.close_round = round_no
        ctx.latency = now - ctx.admit_t
        tail = now - ctx.last_t
        if tail > 0:
            ctx.segments["drain"] = ctx.segments.get("drain", 0.0) + tail
        self.requests_closed += 1
        self.sample_exemplar(cause, ctx.latency, ctx)
        if self.slo is not None:
            # a dropped request (shed / quarantined) BURNS error
            # budget regardless of how fast it was dropped — dropped
            # traffic reading as SLO-compliant would let a mass-shed
            # regression sail through the compliance gate
            self.slo.note_request(
                ctx.budget_class, ctx.latency, doc_id, ctx.segments,
                dropped=cause in ("shed", "quarantined"),
            )
        self._samples.append(ctx)
        return ctx.latency

    def sample_exemplar(self, tag: str, latency_s: float,
                        ctx: RequestContext) -> None:
        """Attach ``ctx`` to the drain-latency histogram bucket its
        latency lands in (``bisect_left`` over the same bounds the
        histogram observes with, so exemplar and count always agree;
        the LAST request per bucket wins).  An admission/drain-edge
        call — G012 bans it in per-op inner loops."""
        bounds = self._bounds.get(tag)
        if bounds is None:
            return
        i = bisect_left(bounds, float(latency_s))
        self.exemplars.setdefault(tag, {})[i] = ctx.to_dict()

    # ---- per-round folding (hot path; armed-only by the caller) ----

    def round_begin(self) -> None:
        """Reset the round's segment/hop accumulators (no-op
        disarmed)."""
        if not self.armed:
            return
        # trailing attribution: publishes observed AFTER the round's
        # fold — the end-of-round status snapshot (telemetry.note_round
        # enters StatusServer.publish_*) — still carry the folded
        # round's data, so they union into the prior lane set's
        # still-active contexts before the accumulators reset (without
        # this, the status edge would be unreachable by any trace on a
        # clean drain: every other publish fires between note_scheduled
        # and fold_round)
        if self._round_hops and self._round_docs:
            for doc_id in self._round_docs:
                ctx = self._active.get(doc_id)
                if ctx is not None:
                    ctx.hops |= self._round_hops
        self._round_segs = {}
        self._round_hops = set()
        self._round_docs = set()

    def note_scheduled(self, doc_ids) -> None:
        """Register this round's lane set — the docs whose data rides
        this round's publish points.  Hops observed during the round
        attribute only to these docs' contexts (see
        :meth:`close_request`); called once per round right after the
        plan is final, before the WAL publish fires."""
        if not self.armed:
            return
        self._round_docs = set(doc_ids)

    def segment(self, name: str):
        """Time one macro-round phase: ``with rt.segment("plan"):``.
        Disarmed this is the shared :data:`NOOP_SEGMENT`."""
        if not self.armed:
            return NOOP_SEGMENT
        return _Segment(self, name)

    def fold_round(self, round_no: int,
                   docs: list[tuple[int, int]]) -> None:
        """Fold this round's phase timings, publish hops, and per-doc
        op counts into every scheduled doc's active context.  The
        causal attribution rule: a doc scheduled this round spent this
        round's phases; time since its last fold NOT covered by phases
        is ``queue`` wait."""
        now = time.perf_counter()
        segs = self._round_segs
        seg_total = sum(segs.values())
        hops = self._round_hops
        for doc_id, ops in docs:
            ctx = self._active.get(doc_id)
            if ctx is None:
                continue
            elapsed = now - ctx.last_t
            gap = elapsed - seg_total
            scale = 1.0
            if gap > 0:
                ctx.segments["queue"] = (
                    ctx.segments.get("queue", 0.0) + gap
                )
            elif seg_total > 0:
                # admitted mid-round (its clock started inside a
                # phase): credit only its share of the phases, so
                # sum(segments) never exceeds the request's latency
                scale = max(0.0, elapsed) / seg_total
            for k, v in segs.items():
                ctx.segments[k] = ctx.segments.get(k, 0.0) + v * scale
            if hops:
                ctx.hops |= hops
            ctx.ops += ops
            ctx.rounds += 1
            ctx.last_t = now

    def note_remote(self, doc_id: int, by_writer: dict[int, int]) -> None:
        """Attribute remote-merged ops to their originating writers
        (replicated fleets; armed-only by the caller)."""
        ctx = self._active.get(doc_id)
        if ctx is None:
            return
        for w, n in by_writer.items():
            ctx.remote_ops[w] = ctx.remote_ops.get(w, 0) + n

    # ---- surfaces ----

    def sampled(self) -> list[dict]:
        """The ring of most recently closed request traces, oldest
        first."""
        return [ctx.to_dict() for ctx in self._samples]

    def dump_requests(self) -> list[dict]:
        """Flight-recorder material: the sampled ring PLUS every still-
        open request (a crash post-mortem wants the in-flight set)."""
        out = self.sampled()
        for doc_id in sorted(self._active):
            out.append(self._active[doc_id].to_dict())
        return out

    def block(self) -> dict:
        """The versioned ``reqtrace`` artifact block."""
        return {
            "version": REQTRACE_VERSION,
            "armed": self.armed,
            "samples_cap": self.samples_cap,
            "requests_opened": self.requests_opened,
            "requests_closed": self.requests_closed,
            "reopened": self.reopened,
            "active": len(self._active),
            "hops": dict(sorted(self.hop_counts.items())),
            "exemplars": {
                tag: {str(i): ex for i, ex in sorted(buckets.items())}
                for tag, buckets in sorted(self.exemplars.items())
            },
            "traces": self.sampled(),
        }
