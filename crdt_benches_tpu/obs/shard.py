"""Mesh-aware per-shard serve metrics (+ per-replica merge series).

Every fleet number the registry carried before this module was a
*fleet-wide* aggregate: under ``--serve-mesh`` the run could be pinned
to one hot device while seven idled and no artifact field would say so.
:class:`ShardMetrics` splits the load signals by mesh shard:

- ``serve.shard.ops{shard="s"}`` / ``serve.shard.unit_ops{...}`` — range
  ops / unit-op equivalents applied to documents resident on shard
  ``s`` (host-known: a lane's shard is ``row // Rg``, no device sync);
- ``serve.shard.lanes{...}`` — scheduled lane-rounds per shard (the
  occupancy numerator, summed over rounds);
- ``serve.shard.occupancy{...}`` — resident-row fraction of the shard's
  row budget, gauged per round;
- ``serve.shard.relocations{...}`` — cross-shard row moves (promotions
  or compaction pulls whose source lived on a different shard);
- ``serve.shard.imbalance`` — max/mean of per-round scheduled lanes
  across shards: 1.0 = perfectly balanced, R = everything on one shard;
- ``serve.shard.mem_bytes_in_use{...}`` — device allocator stats where
  the backend exposes ``Device.memory_stats()`` (real TPUs do; the
  virtual CPU mesh reports nothing and the gauges simply stay unset).

**Sum parity is the contract** (tested): for every time-series window,
the per-shard ops/lanes sums equal the fleet totals the pre-mesh
artifact already reported — shard residency is a partition, never a
second accounting.

Label convention: series names carry their label set Prometheus-style
(``base{shard="0"}``) directly in the registry key; the ``/metrics``
renderer (:mod:`obs.status`) parses it back into real labels.  All
series are pre-registered here, at bind time — the per-round path only
touches held references (graftlint G013 bans registry get-or-create in
hot scopes).
"""

from __future__ import annotations

from .metrics import MetricsRegistry


def labeled(base: str, shard: int) -> str:
    """Registry key for a shard-labeled series."""
    return f'{base}{{shard="{shard}"}}'


class ShardMetrics:
    """Per-shard load/residency series over one drain's registry."""

    def __init__(self, pool, registry: MetricsRegistry):
        self.pool = pool
        self.n_sh = pool.n_sh
        rng = range(self.n_sh)
        self._ops = [
            registry.counter(labeled("serve.shard.ops", s)) for s in rng
        ]
        self._units = [
            registry.counter(labeled("serve.shard.unit_ops", s))
            for s in rng
        ]
        self._lanes = [
            registry.counter(labeled("serve.shard.lanes", s)) for s in rng
        ]
        self._reloc = [
            registry.counter(labeled("serve.shard.relocations", s))
            for s in rng
        ]
        self._occ = [
            registry.gauge(labeled("serve.shard.occupancy", s))
            for s in rng
        ]
        self._mem = [
            registry.gauge(labeled("serve.shard.mem_bytes_in_use", s))
            for s in rng
        ]
        self.imbalance = registry.gauge("serve.shard.imbalance")
        self._rows_per_shard = [
            sum(b.Rg for b in pool.buckets.values()) for _ in rng
        ]

    # ---- hot path (pre-registered references only) ----

    def note_round(self, shard_lanes, shard_ops, shard_units) -> None:
        """Fold one macro-round's per-shard tallies into the series and
        gauge the imbalance (max/mean of scheduled lanes; 1.0 when no
        lane ran — an idle round is balanced, not degenerate)."""
        total = 0
        peak = 0
        occupied = self.pool.shard_occupancy()
        for s in range(self.n_sh):
            lanes = shard_lanes[s]
            total += lanes
            if lanes > peak:
                peak = lanes
            if shard_ops[s]:
                self._ops[s].inc(shard_ops[s])
                self._units[s].inc(shard_units[s])
            if lanes:
                self._lanes[s].inc(lanes)
            self._occ[s].set(occupied[s] / self._rows_per_shard[s])
        self.imbalance.set(
            peak * self.n_sh / total if total else 1.0
        )

    def note_relocation(self, dst_shard: int) -> None:
        """One row moved onto ``dst_shard`` from a different shard."""
        self._reloc[dst_shard].inc()

    # ---- window cadence (still host-only; allocator stats are a
    # local device query, not a sync) ----

    def sample_memory(self) -> None:
        from ..parallel.mesh import device_memory_stats

        for s, ms in enumerate(device_memory_stats(self.n_sh)):
            if ms is not None and "bytes_in_use" in ms:
                self._mem[s].set(float(ms["bytes_in_use"]))


def class_labeled(base: str, cls: int) -> str:
    """Registry key for a capacity-class-labeled series."""
    return f'{base}{{doc_class="{cls}"}}'


class ReplicaMetrics:
    """Replication-fleet series over one drain's registry
    (serve/replicate/): the remote-merge load split by the capacity
    class it landed in, plus the bus-level health signals.

    - ``serve.replica.merged_ops{doc_class="c"}`` /
      ``serve.replica.merged_unit_ops{...}`` — remote (broadcast) range
      ops / unit-op equivalents merged into replica rows of class
      ``c``; **sum parity is the contract** (tested, the same
      discipline as the per-shard series): the per-class counters
      partition the drain's total merged-op count — remote-merge
      attribution is a partition of the merge work, never a second
      accounting;
    - ``serve.replica.local_ops`` — the upstream half (a writer's own
      ops applied to its own replica), so local + merged partition the
      fleet's total applied ops;
    - ``serve.replica.divergence_depth`` — gauge: the deepest
      per-replica broadcast lag this round, in turn blocks (published
      head minus the replica's assembled prefix);
    - ``serve.replica.broadcast_bytes`` / ``broadcast_blocks`` — packed
      op-lane bytes / turn blocks actually delivered to REMOTE replicas
      (the fan-out cost of the writer group; local self-delivery is
      free and not counted).

    All series are pre-registered here, at bind time — the per-round
    path only touches held references (graftlint G013)."""

    def __init__(self, registry: MetricsRegistry, classes):
        self._merged = {
            c: registry.counter(class_labeled(
                "serve.replica.merged_ops", c
            ))
            for c in classes
        }
        self._merged_units = {
            c: registry.counter(class_labeled(
                "serve.replica.merged_unit_ops", c
            ))
            for c in classes
        }
        self.local_ops = registry.counter("serve.replica.local_ops")
        self.divergence = registry.gauge("serve.replica.divergence_depth")
        self.broadcast_bytes = registry.counter(
            "serve.replica.broadcast_bytes"
        )
        self.broadcast_blocks = registry.counter(
            "serve.replica.broadcast_blocks"
        )

    # ---- hot path (pre-registered references only) ----

    def note_merged(self, cls: int, ops: int, unit_ops: int) -> None:
        """Remote ops merged into a class-``cls`` replica row."""
        self._merged[cls].inc(ops)
        self._merged_units[cls].inc(unit_ops)

    def note_local(self, ops: int) -> None:
        self.local_ops.inc(ops)

    def note_divergence(self, depth_blocks: int) -> None:
        self.divergence.set(float(depth_blocks))

    def note_broadcast(self, nbytes: int, blocks: int = 1) -> None:
        self.broadcast_bytes.inc(nbytes)
        self.broadcast_blocks.inc(blocks)

    def merged_total(self) -> tuple[int, int]:
        """(ops, unit_ops) summed over every class label — the parity
        side the tests compare against the scheduler's totals."""
        return (
            sum(c.value for c in self._merged.values()),
            sum(c.value for c in self._merged_units.values()),
        )
