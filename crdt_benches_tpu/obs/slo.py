"""Per-class latency SLOs: objectives, burn rates, compliance.

PR 7's telemetry says what the fleet is doing; this module says whether
it is doing it *well enough to admit more work* — the accounting the
ROADMAP's deadline/SLO-aware scheduler admits against.  An **objective**
binds a latency budget class to a quantile target (``--serve-slo``
grammar: ``class=pQ:MS``, e.g. ``default=p99:250,c4096=p99.9:1500`` —
"99% of class-c4096 requests drain within 1.5s").  Every closed doc
request (``obs/reqtrace.py``) lands here as one observation:

- **compliance** — the fraction of the class's requests inside the
  objective, cumulative over the drain (the artifact's headline; gated
  by ``tools/bench_compare.py`` against the baseline);
- **burn rate** — violations consumed per unit of error budget, over
  TWO rolling request windows (fast ~64 / slow ~512 requests, the
  multi-window pattern that separates a blip from a sustained burn:
  fast >> 1 with slow ~ 1 is a spike; both elevated is an incident).
  Burn 1.0 = exactly on budget (a p99 objective tolerating 1%
  violations is *expected* to run at 1.0), >1 = the budget is burning
  faster than it refills.  Exported live as pre-registered gauges
  (``serve.slo.burn_rate{class="c",window="fast|slow"}``) on the
  Prometheus endpoint and folded into ``/status.json``;
- **top-K slowest docs** — the worst requests with their per-segment
  breakdowns (queue/stage/dispatch/drain, from the request trace), so
  "the p99.9 is burning" links to *which* docs and *where* their time
  went.

Budget classes derive from the doc's capacity class at admission
(``c256`` .. ``c49152``); ``default`` catches everything the spec does
not name.  Classification happens once per request at admission — the
hot path holds pre-registered gauge references only (graftlint G013).

Thread confinement: the tracker is owned by the **hot** thread — every
observation happens at a request close inside the macro-round; what
readers see is the snapshot the status publisher swaps out.
"""

from __future__ import annotations

import math
from collections import deque

#: Bump when the ``slo`` artifact block changes shape.
SLO_VERSION = 1

#: Rolling burn-rate windows, in REQUESTS (not wall time): request
#: arrival is what the admission scheduler will pace, and request
#: windows keep the math identical across fleet sizes.
FAST_WINDOW = 64
SLOW_WINDOW = 512

#: Slowest requests retained with segment breakdowns.
DEFAULT_TOP_K = 8


class SloSpecError(ValueError):
    """A ``--serve-slo`` spec that does not parse MUST fail the run —
    a typo'd objective silently gating nothing is worse than none."""


class SloObjective:
    """One class's latency objective: quantile target + threshold."""

    __slots__ = ("name", "quantile", "threshold_s")

    def __init__(self, name: str, quantile: float, threshold_s: float):
        if not name:
            raise SloSpecError(
                "slo class name must be non-empty (classify() could "
                "never route a request to it)"
            )
        if not (0.0 < quantile < 1.0):
            raise SloSpecError(
                f"slo class {name!r}: quantile must be in (0, 1), "
                f"got {quantile}"
            )
        # nan passes a bare `<= 0` check (nan <= 0 is False) and then
        # every `latency > nan` is False — an objective that silently
        # gates nothing, exactly what SloSpecError exists to prevent
        if not math.isfinite(threshold_s) or threshold_s <= 0:
            raise SloSpecError(
                f"slo class {name!r}: threshold must be finite "
                f"positive ms, got {threshold_s * 1e3:g}"
            )
        self.name = name
        self.quantile = quantile
        self.threshold_s = threshold_s

    @property
    def budget(self) -> float:
        """Tolerated violation fraction (1 - quantile)."""
        return 1.0 - self.quantile

    def to_dict(self) -> dict:
        return {
            "quantile": self.quantile,
            "threshold_ms": self.threshold_s * 1e3,
        }


def parse_slo_spec(spec: str) -> dict[str, SloObjective]:
    """THE ``--serve-slo`` grammar: comma-separated ``class=pQ:MS``.
    ``class`` is a budget class (``default`` or a capacity class like
    ``c4096``), ``pQ`` a percentile (``p99``, ``p99.9``), ``MS`` the
    latency threshold in milliseconds.  Raises :class:`SloSpecError`
    on anything malformed."""
    out: dict[str, SloObjective] = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise SloSpecError(
                f"slo spec {part!r}: expected class=pQ:MS "
                "(e.g. default=p99:250)"
            )
        name, rest = part.split("=", 1)
        name = name.strip()
        if ":" not in rest:
            raise SloSpecError(
                f"slo spec {part!r}: expected pQ:MS after '='"
            )
        q_s, ms_s = rest.split(":", 1)
        q_s = q_s.strip().lower()
        if not q_s.startswith("p"):
            raise SloSpecError(
                f"slo spec {part!r}: quantile must be spelled pQ "
                "(p99, p99.9)"
            )
        try:
            quantile = float(q_s[1:]) / 100.0
            threshold_s = float(ms_s) / 1e3
        except ValueError as e:
            raise SloSpecError(f"slo spec {part!r}: {e}") from None
        if name in out:
            raise SloSpecError(f"slo class {name!r} given twice")
        out[name] = SloObjective(name, quantile, threshold_s)
    if not out:
        raise SloSpecError(f"slo spec {spec!r} names no objective")
    return out


def class_window_key(name: str, window: str) -> str:
    """Registry key for a burn-rate gauge (labels parsed back out by
    the Prometheus renderer in obs/status.py)."""
    return f'serve.slo.burn_rate{{class="{name}",window="{window}"}}'


def compliance_key(name: str) -> str:
    return f'serve.slo.compliance{{class="{name}"}}'


class _ClassState:
    __slots__ = ("objective", "requests", "violations", "fast", "slow",
                 "g_fast", "g_slow", "g_comp")

    def __init__(self, objective: SloObjective):
        self.objective = objective
        self.requests = 0
        self.violations = 0
        self.fast: deque[bool] = deque(maxlen=FAST_WINDOW)
        self.slow: deque[bool] = deque(maxlen=SLOW_WINDOW)
        self.g_fast = None
        self.g_slow = None
        self.g_comp = None

    @staticmethod
    def _burn(window: deque, budget: float) -> float:
        if not window:
            return 0.0
        frac = sum(window) / len(window)
        return frac / budget

    def note(self, violation: bool) -> None:
        self.requests += 1
        self.violations += int(violation)
        self.fast.append(violation)
        self.slow.append(violation)
        if self.g_fast is not None:
            b = self.objective.budget
            self.g_fast.set(self._burn(self.fast, b))
            self.g_slow.set(self._burn(self.slow, b))
            self.g_comp.set(self.compliance)

    @property
    def compliance(self) -> float:
        if not self.requests:
            return 1.0
        return 1.0 - self.violations / self.requests

    def to_dict(self) -> dict:
        b = self.objective.budget
        return {
            "objective": self.objective.to_dict(),
            "requests": self.requests,
            "violations": self.violations,
            "compliance": self.compliance,
            "burn_rate_fast": self._burn(self.fast, b),
            "burn_rate_slow": self._burn(self.slow, b),
        }


class SloTracker:  # graftlint: thread=hot
    """Per-class SLO accounting over closed doc requests (module
    docstring has the model).  Gauges are pre-registered at
    :meth:`bind`; :meth:`note_request` touches held references only."""

    def __init__(self, objectives: dict[str, SloObjective],
                 top_k: int = DEFAULT_TOP_K):
        self.objectives = dict(objectives)
        self.classes = {
            name: _ClassState(obj) for name, obj in objectives.items()
        }
        self.top_k = max(1, int(top_k))
        # top-K slowest requests: a sorted ascending list bounded at K,
        # so the head is the eviction candidate (K is single digits —
        # an insertion beats heap bookkeeping at this size)
        self._slowest: list[tuple[float, int, dict]] = []
        self._seq = 0
        self.unclassified = 0  # requests no objective claims

    @classmethod
    def from_spec(cls, spec: str, top_k: int = DEFAULT_TOP_K
                  ) -> "SloTracker":
        return cls(parse_slo_spec(spec), top_k=top_k)

    # ---- driver-side wiring ----

    def bind(self, registry) -> None:
        """Pre-register every gauge in the drain's registry (G013: the
        per-request path must never get-or-create)."""
        for name, st in self.classes.items():
            st.g_fast = registry.gauge(class_window_key(name, "fast"))
            st.g_slow = registry.gauge(class_window_key(name, "slow"))
            st.g_comp = registry.gauge(compliance_key(name))

    # ---- admission-time classification ----

    def classify(self, capacity_class: int | None) -> str:
        """Budget class for a doc admitted into ``capacity_class``:
        the class's own objective (``c4096``) when the spec names one,
        else ``default``.  Returns the class name even when no
        objective claims it — the request trace still carries it."""
        if capacity_class is not None:
            name = f"c{capacity_class}"
            if name in self.classes:
                return name
        if "default" in self.classes:
            return "default"
        return f"c{capacity_class}" if capacity_class is not None \
            else "default"

    # ---- per-request accounting (hot path; held references only) ----

    def note_request(self, name: str, latency_s: float, doc_id: int,
                     segments: dict | None = None, *,
                     dropped: bool = False) -> None:
        """One closed request: a violation when it missed its latency
        objective OR was dropped (shed/quarantined) — a request the
        service failed to serve never satisfies the objective, however
        quickly it was dropped."""
        st = self.classes.get(name)
        if st is None:
            self.unclassified += 1
            return
        st.note(dropped or latency_s > st.objective.threshold_s)
        self._seq += 1
        slow = self._slowest
        if len(slow) >= self.top_k and latency_s <= slow[0][0]:
            return  # common case: not a top-K entry, allocate nothing
        entry = (latency_s, self._seq, {
            "doc": doc_id,
            "class": name,
            "latency_s": latency_s,
            "segments": dict(segments) if segments else {},
        })
        if len(slow) < self.top_k:
            slow.append(entry)
            slow.sort(key=lambda e: (e[0], e[1]))
        else:
            slow[0] = entry
            slow.sort(key=lambda e: (e[0], e[1]))

    # ---- surfaces ----

    def slowest(self) -> list[dict]:
        """Top-K slowest requests, worst first, with segment
        breakdowns."""
        return [e[2] for e in sorted(
            self._slowest, key=lambda e: (-e[0], e[1])
        )]

    def status_fields(self) -> dict:
        """The ``/status.json`` view: per-class burn/compliance plus
        the current top-K (plain scalars/lists — published verbatim)."""
        b = {
            name: {
                "burn_fast": st._burn(st.fast, st.objective.budget),
                "burn_slow": st._burn(st.slow, st.objective.budget),
                "compliance": st.compliance,
                "requests": st.requests,
            }
            for name, st in sorted(self.classes.items())
        }
        return {"classes": b, "slow_docs": self.slowest()}

    def block(self) -> dict:
        """The versioned ``slo`` artifact block."""
        return {
            "version": SLO_VERSION,
            "windows": {"fast": FAST_WINDOW, "slow": SLOW_WINDOW},
            "classes": {
                name: st.to_dict()
                for name, st in sorted(self.classes.items())
            },
            "unclassified": self.unclassified,
            "slow_docs": self.slowest(),
        }
