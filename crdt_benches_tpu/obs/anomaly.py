"""Online anomaly detection over the serve time-series (``--serve-soak``).

A soak run is only useful if degradation is *caught*, not eyeballed out
of a 10k-window artifact afterwards.  :class:`AnomalyDetector` consumes
the same stream the recorder folds (per-round latencies, closed
windows) and maintains three online detectors:

- **stuck-round watchdog** (per round): a macro-round whose wall time
  exceeds the watchdog threshold — explicit ``watchdog_s``, or
  ``watchdog_factor`` x the rolling median of steady rounds (floored at
  ``watchdog_min_s``) — fires ``stuck_round``; the next on-time round
  clears it.  Compile- and barrier-flagged rounds are exempt (they are
  *known* slow, the same exemption the latency quantiles apply), so a
  chaos ``stall`` fault is exactly what trips it;
- **throughput degradation** (per window): robust location/scale over
  the window throughput history (median/MAD); a full window below
  ``median - mad_k * 1.4826 * MAD`` AND below ``(1 - drop_frac) *
  median`` fires ``throughput_degradation``.  Windows whose occupancy
  has collapsed relative to history are skipped — a fleet legitimately
  draining down is not a regression — and anomalous windows are kept
  out of the history so a real degradation cannot normalize itself;
- **monotonic growth / leak** (per window): resident-set size and
  journal bytes-per-op that rise strictly across the last
  ``leak_windows`` full windows by more than ``leak_frac`` fire
  ``rss_leak`` / ``journal_growth``; a plateau clears them.

Every fire/clear lands in :attr:`events` (the artifact's versioned
``anomalies`` block) and the active set feeds ``/healthz``.  The run's
exit-code contract: anomalies that fired AND cleared are history (a
stall the engine absorbed is a demonstration, not a failure); an
anomaly still active at drain end fails the run.
"""

from __future__ import annotations

from collections import deque
from statistics import median

#: Bump when the ``anomalies`` artifact block changes shape.
ANOMALIES_VERSION = 1


class AnomalyDetector:
    """Shared-nothing online detectors; pure host arithmetic per call."""

    def __init__(self, *, watchdog_s: float = 0.0,
                 watchdog_factor: float = 25.0, watchdog_min_s: float = 1.0,
                 mad_k: float = 5.0, drop_frac: float = 0.5,
                 min_windows: int = 6, history: int = 64,
                 leak_windows: int = 8, leak_frac: float = 0.25):
        self.watchdog_s = float(watchdog_s)
        self.watchdog_factor = watchdog_factor
        self.watchdog_min_s = watchdog_min_s
        self.mad_k = mad_k
        self.drop_frac = drop_frac
        self.min_windows = min_windows
        self.leak_windows = max(3, int(leak_windows))
        self.leak_frac = leak_frac
        self.events: list[dict] = []
        self._active: dict[str, dict] = {}
        self._lat = deque(maxlen=64)
        self._tput = deque(maxlen=history)
        self._occ = deque(maxlen=history)
        self._rss = deque(maxlen=history)
        self._jrate = deque(maxlen=history)

    # ---- event bookkeeping ----

    def _fire(self, kind: str, round_no: int, value: float,
              threshold: float, **detail) -> None:
        ev = self._active.get(kind)
        if ev is not None:
            ev["last_round"] = round_no
            ev["rounds_active"] += 1
            return
        ev = {
            "kind": kind,
            "round": round_no,
            "last_round": round_no,
            "rounds_active": 1,
            "value": value,
            "threshold": threshold,
            "cleared": False,
            "cleared_round": None,
            "detail": detail,
        }
        self._active[kind] = ev
        self.events.append(ev)

    def _clear(self, kind: str, round_no: int) -> None:
        ev = self._active.pop(kind, None)
        if ev is not None:
            ev["cleared"] = True
            ev["cleared_round"] = round_no

    def active_kinds(self) -> list[str]:
        return sorted(self._active)

    @property
    def fired(self) -> int:
        return len(self.events)

    @property
    def uncleared(self) -> int:
        return len(self._active)

    # ---- per-round: the stuck-round watchdog ----

    def _watchdog_threshold(self) -> float | None:
        if self.watchdog_s > 0:
            return self.watchdog_s
        if len(self._lat) < 8:
            return None  # auto mode needs a latency baseline first
        return max(
            self.watchdog_min_s, self.watchdog_factor * median(self._lat)
        )

    def note_round(self, seconds: float, *, skip: bool,
                   round_no: int) -> None:
        """One macro-round's wall time.  ``skip`` marks compile /
        snapshot-barrier rounds — known-slow, excluded from both the
        threshold check and the rolling baseline."""
        if skip:
            return
        thr = self._watchdog_threshold()
        if thr is not None and seconds > thr:
            self._fire("stuck_round", round_no, seconds, thr)
            return  # a stalled round must not drag the baseline up
        if thr is not None:
            self._clear("stuck_round", round_no)
        self._lat.append(seconds)

    # ---- per-window: throughput + leak detectors ----

    @staticmethod
    def _monotonic_growth(hist: deque, n: int) -> float | None:
        """Relative growth over the last ``n`` samples IF they rise
        strictly; None otherwise (or with too little history)."""
        if len(hist) < n:
            return None
        tail = list(hist)[-n:]
        if any(b <= a for a, b in zip(tail, tail[1:])):
            return None
        if tail[0] <= 0:
            return None
        return tail[-1] / tail[0] - 1.0

    def note_window(self, w: dict) -> None:
        """One closed time-series window (an `obs/timeseries.py` window
        dict).  Partial windows only feed the leak history."""
        round_no = w.get("end_round", 0)
        rss = w.get("rss_bytes")
        if rss:
            self._rss.append(rss)
            g = self._monotonic_growth(self._rss, self.leak_windows)
            if g is not None and g >= self.leak_frac:
                self._fire("rss_leak", round_no, float(rss), g,
                           windows=self.leak_windows)
            else:
                self._clear("rss_leak", round_no)
        if w.get("journal_bytes") and w.get("ops"):
            self._jrate.append(w["journal_bytes"] / w["ops"])
            g = self._monotonic_growth(self._jrate, self.leak_windows)
            if g is not None and g >= self.leak_frac:
                self._fire("journal_growth", round_no,
                           self._jrate[-1], g,
                           windows=self.leak_windows)
            else:
                self._clear("journal_growth", round_no)
        if not w.get("full"):
            return  # rate checks need comparable window lengths
        tput = w.get("throughput", 0.0)
        occ = w.get("occupancy", 0.0)
        if len(self._tput) >= self.min_windows:
            med = median(self._tput)
            mad = median(abs(x - med) for x in self._tput)
            occ_med = median(self._occ) if self._occ else 0.0
            draining = occ_med > 0 and occ < 0.5 * occ_med
            low = (
                med > 0
                and tput < med - self.mad_k * 1.4826 * mad
                and tput < (1.0 - self.drop_frac) * med
            )
            if low and not draining:
                self._fire("throughput_degradation", round_no, tput,
                           med, mad=mad, median=med)
                return  # keep the degraded window out of the baseline
            self._clear("throughput_degradation", round_no)
        self._tput.append(tput)
        self._occ.append(occ)

    # ---- artifact surface ----

    def block(self) -> dict:
        """The versioned ``anomalies`` artifact block."""
        return {
            "version": ANOMALIES_VERSION,
            "watchdog_s": self.watchdog_s or None,
            "fired": self.fired,
            "uncleared": self.uncleared,
            "active": self.active_kinds(),
            "events": [dict(e) for e in self.events],
        }
