"""Phase-span tracer: Chrome trace events, zero overhead when disarmed.

The serving hot path is instrumented with ``with span("serve.plan"):``
blocks.  Disarmed (the default), :func:`span` returns one shared no-op
context manager — no allocation, no clock read, no branch beyond a
module-global ``is None`` test; the contract is asserted by
``tests/test_obs.py`` the same way the ``@boundary`` identity path is.
Armed (:func:`arm`, driven by ``--serve-trace`` or
``CRDT_BENCH_TRACE=1``), every span records one Chrome trace-event
``"X"`` (complete) entry and every declared-fence crossing from
``lint/sanitizer.py`` lands as a ``"i"`` (instant) event *inside the
span that owns it* — load the file in Perfetto (or
``chrome://tracing``) and the G011 fence model is drawn on the
macro-round timeline.

Naming convention (enforced in hot scopes by graftlint G012): span and
metric names are **registered constants** — dotted lowercase
(``serve.plan``, ``serve.dispatch``), never f-strings.  Dynamic context
goes in the ``args`` payload, where it belongs.

The module doubles as the trace schema validator::

    python -m crdt_benches_tpu.obs.trace bench_results/serve_trace.json

exits nonzero unless the file is well-formed Chrome trace JSON, spans
nest properly per thread, and every fence instant lies inside its
owning span (the smoke's traced leg gates on this).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

_ENV = "CRDT_BENCH_TRACE"

#: Chrome trace "cat" for declared-fence instant events.
FENCE_CAT = "fence"


class _NoopSpan:
    """The disarmed span: one shared instance, nothing in enter/exit."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class _Span:
    """One armed span: records a complete ("X") event on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, args: dict):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = self._tracer.now_us()
        self._tracer._stack().append(self._name)
        return self

    def __exit__(self, *exc):
        tr = self._tracer
        t1 = tr.now_us()
        tr._stack().pop()
        ev = {
            "ph": "X",
            "name": self._name,
            "ts": self._t0,
            "dur": t1 - self._t0,
            "pid": tr.pid,
            "tid": threading.get_ident() & 0xFFFF,
        }
        if self._args:
            ev["args"] = self._args
        tr.events.append(ev)
        return False


class SpanTracer:
    """Collects Chrome trace events for one armed window.

    Spans nest via a per-thread name stack (used to attribute fence
    instants to their owning span); events are buffered in memory and
    written once by :meth:`write` — a drain emits a few events per
    macro-round, so the buffer stays tiny next to the fleet state.
    """

    def __init__(self):
        self.events: list[dict] = []
        self.pid = os.getpid() & 0xFFFF
        self._origin = time.perf_counter()
        self._tls = threading.local()

    def now_us(self) -> float:
        return (time.perf_counter() - self._origin) * 1e6

    def _stack(self) -> list[str]:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args)

    def instant(self, name: str, cat: str | None = None, **args) -> None:
        stack = self._stack()
        if stack:
            args = dict(args, span=stack[-1])
        ev = {
            "ph": "i",
            "s": "t",
            "name": name,
            "ts": self.now_us(),
            "pid": self.pid,
            "tid": threading.get_ident() & 0xFFFF,
        }
        if cat:
            ev["cat"] = cat
        if args:
            ev["args"] = args
        self.events.append(ev)

    def _on_fence(self, qualname: str) -> None:
        """Sanitizer fence-entry observer: one instant per crossing."""
        self.instant(qualname, cat=FENCE_CAT)

    def to_dict(self) -> dict:
        return {"traceEvents": list(self.events), "displayTimeUnit": "ms"}

    def write(self, path: str) -> str:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f)
        return path


#: The armed tracer, or None (disarmed).  Module-global on purpose: the
#: hot path pays exactly one load + None test when disarmed.
_tracer: SpanTracer | None = None


def env_armed() -> bool:
    """True when ``CRDT_BENCH_TRACE`` requests arming (read at bench
    start, not at import, so tests can flip it)."""
    return os.environ.get(_ENV, "") not in ("", "0")


def armed() -> bool:
    return _tracer is not None


def arm() -> SpanTracer:
    """Install a fresh tracer and hook the sanitizer's fence-entry
    observer so every ``@fenced`` crossing lands on the timeline.
    NEVER call from a hot scope (G012 flags it): arming belongs to the
    bench driver, before the drain starts."""
    global _tracer
    from ..lint import sanitizer

    disarm()
    _tracer = SpanTracer()
    sanitizer.add_fence_observer(_tracer._on_fence)
    return _tracer


def disarm() -> SpanTracer | None:
    """Remove the tracer (and its fence hook); returns it so the caller
    can :meth:`SpanTracer.write` the collected events."""
    global _tracer
    t, _tracer = _tracer, None
    if t is not None:
        from ..lint import sanitizer

        sanitizer.remove_fence_observer(t._on_fence)
    return t


def span(name: str, **args):
    """A phase span: ``with span("serve.plan"):``.  Disarmed this is
    the shared :data:`NOOP_SPAN`; armed it records one "X" event."""
    t = _tracer
    if t is None:
        return NOOP_SPAN
    return t.span(name, **args)  # graftlint: disable=G012 (API plumbing)


def instant(name: str, **args) -> None:
    """A point event on the current span (no-op when disarmed)."""
    t = _tracer
    if t is not None:
        t.instant(name, **args)


# ---------------------------------------------------------------------------
# schema validation (the smoke's traced leg gates on this)
# ---------------------------------------------------------------------------

_REQUIRED = ("ph", "name", "ts", "pid", "tid")


def validate_trace(data) -> list[str]:
    """Structural checks on a Chrome trace document: every event
    well-formed, "X" spans properly nested per (pid, tid) — partial
    overlap means a corrupted stack — and every ``cat=fence`` instant
    inside its owning span.  Returns a list of problems (empty = valid).
    """
    errors: list[str] = []
    if not isinstance(data, dict) or not isinstance(
        data.get("traceEvents"), list
    ):
        return ["top level must be a dict with a traceEvents list"]
    events = data["traceEvents"]
    if not events:
        errors.append("traceEvents is empty")
    spans_by_tid: dict[tuple, list[dict]] = {}
    instants: list[dict] = []
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            errors.append(f"event {i}: not an object")
            continue
        missing = [k for k in _REQUIRED if k not in ev]
        if missing:
            errors.append(f"event {i}: missing {missing}")
            continue
        if not isinstance(ev["name"], str) or not ev["name"]:
            errors.append(f"event {i}: name must be a non-empty string")
        if not isinstance(ev["ts"], (int, float)) or ev["ts"] < 0:
            errors.append(f"event {i}: bad ts {ev['ts']!r}")
            continue
        if ev["ph"] == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"event {i} ({ev['name']}): bad dur {dur!r}")
                continue
            spans_by_tid.setdefault(
                (ev["pid"], ev["tid"]), []
            ).append(ev)
        elif ev["ph"] == "i":
            instants.append(ev)
        elif ev["ph"] not in ("I", "M", "C"):
            errors.append(f"event {i}: unknown ph {ev['ph']!r}")
    # span nesting: on one thread, two spans either nest or are disjoint
    for tid, spans in spans_by_tid.items():
        spans = sorted(spans, key=lambda e: (e["ts"], -e["dur"]))
        open_stack: list[dict] = []
        for ev in spans:
            while open_stack and (
                open_stack[-1]["ts"] + open_stack[-1]["dur"] <= ev["ts"]
            ):
                open_stack.pop()
            if open_stack:
                top = open_stack[-1]
                if ev["ts"] + ev["dur"] > top["ts"] + top["dur"] + 1e-6:
                    errors.append(
                        f"span `{ev['name']}` (ts={ev['ts']:.1f}) "
                        f"partially overlaps `{top['name']}` on tid "
                        f"{tid} — corrupted span stack"
                    )
            open_stack.append(ev)
    # fence instants must land inside their owning span
    for ev in instants:
        if ev.get("cat") != FENCE_CAT:
            continue
        key = (ev["pid"], ev["tid"])
        owner = (ev.get("args") or {}).get("span")
        hits = [
            s for s in spans_by_tid.get(key, [])
            if s["ts"] - 1e-6 <= ev["ts"] <= s["ts"] + s["dur"] + 1e-6
        ]
        if not hits:
            errors.append(
                f"fence instant `{ev['name']}` (ts={ev['ts']:.1f}) lies "
                "inside no span — crossings must be owned by a phase"
            )
        elif owner is not None and owner not in {
            s["name"] for s in hits
        }:
            errors.append(
                f"fence instant `{ev['name']}` claims owning span "
                f"`{owner}` but lies inside {sorted(s['name'] for s in hits)}"
            )
    return errors


def validate_trace_file(path: str) -> list[str]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable trace file: {e}"]
    return validate_trace(data)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m crdt_benches_tpu.obs.trace TRACE.json",
              file=sys.stderr)
        return 2
    errors = validate_trace_file(argv[0])
    for e in errors:
        print(f"{argv[0]}: {e}", file=sys.stderr)
    n_ev = 0
    if not errors:
        with open(argv[0], encoding="utf-8") as f:
            n_ev = len(json.load(f)["traceEvents"])
        print(f"{argv[0]}: valid ({n_ev} events)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
