"""Typed metric registry: Counter / Gauge / fixed-bucket Histogram.

Replaces the grown-by-accretion telemetry lists of ``ServeStats``: a
long drain used to append one float per macro-round to ``occupancy`` /
``queue_depth`` / ``round_latencies`` forever; histograms here hold
O(buckets) state regardless of run length and still answer
p50/p99/p99.9 within bucket resolution.  Everything is stdlib-only and
allocation-light — ``Histogram.observe`` is a bisect + three adds, safe
on the serving hot path (and G002-clean: no numpy, no device traffic).

Design points:

- **fixed, declared buckets**: two histograms with the same bounds are
  *mergeable* (bucket-wise add — associative, the property sharded or
  resumed runs rely on; asserted in tests);
- **quantiles from buckets**: linear interpolation inside the covering
  bucket, clamped to the observed min/max, so a p99 from a histogram
  tracks the exact-list p99 within the bucket's width;
- **versioned serialization**: ``MetricsRegistry.to_dict()`` is the
  serve artifact's ``metrics`` block (``version`` bumps on schema
  change); ``from_dict`` round-trips it losslessly;
- **registered constant names**: dotted lowercase (``serve.pool.
  evictions``).  graftlint G012 rejects f-string metric names in
  hot-path scopes — dynamic context belongs in separate pre-registered
  series (e.g. one drain-latency histogram per cause tag), not in
  name interpolation.
"""

from __future__ import annotations

from bisect import bisect_left

#: Bump when the serialized registry layout changes shape.
METRICS_VERSION = 1


def geometric_bounds(lo: float, hi: float, per_octave: int = 4
                     ) -> tuple[float, ...]:
    """Geometric bucket upper bounds covering [lo, hi] with
    ``per_octave`` buckets per doubling — the relative quantile error
    is bounded by one bucket's ratio (2**(1/per_octave))."""
    if not (0 < lo < hi):
        raise ValueError(f"need 0 < lo < hi, got ({lo}, {hi})")
    factor = 2.0 ** (1.0 / per_octave)
    out = [lo]
    while out[-1] < hi:
        out.append(out[-1] * factor)
    return tuple(out)


#: Macro-round / per-doc latency buckets (seconds): 100us .. ~2min,
#: 4 per octave (~21% resolution).
LATENCY_BUCKETS_S = geometric_bounds(1e-4, 128.0, per_octave=4)

#: Fleet occupancy is a fraction: 20 linear buckets.
OCCUPANCY_BUCKETS = tuple(i / 20.0 for i in range(1, 21))

#: Queue depths / waiting-doc counts: powers of two to 64k.
DEPTH_BUCKETS = (0.0,) + tuple(float(1 << i) for i in range(17))


class Counter:
    """A monotonically increasing integer."""

    __slots__ = ("name", "value")

    def __init__(self, name: str, value: int = 0):
        self.name = name
        self.value = int(value)

    def inc(self, n: int = 1) -> None:
        self.value += n

    def to_dict(self) -> int:
        return self.value


class Gauge:
    """A last-write-wins scalar (plus its observed extrema)."""

    __slots__ = ("name", "value", "vmin", "vmax", "updates")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self.vmin: float | None = None
        self.vmax: float | None = None
        self.updates = 0

    def set(self, v: float) -> None:
        v = float(v)
        self.value = v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)
        self.updates += 1

    def to_dict(self) -> dict:
        return {
            "value": self.value, "min": self.vmin, "max": self.vmax,
            "updates": self.updates,
        }


class Histogram:
    """Fixed-bucket histogram with mergeable buckets.

    ``bounds`` are ascending bucket *upper* edges; an implicit overflow
    bucket catches anything above the last edge.  Exact ``count`` /
    ``total`` / ``min`` / ``max`` ride along, so means are exact and
    quantiles clamp to the observed range.
    """

    __slots__ = ("name", "bounds", "counts", "count", "total",
                 "vmin", "vmax")

    def __init__(self, name: str, bounds):
        bounds = tuple(float(b) for b in bounds)
        if list(bounds) != sorted(set(bounds)):
            raise ValueError(f"bucket bounds not ascending: {bounds}")
        self.name = name
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)
        self.count = 0
        self.total = 0.0
        self.vmin: float | None = None
        self.vmax: float | None = None

    def observe(self, v: float) -> None:
        v = float(v)
        self.counts[bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.total += v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def _bucket_edges(self, i: int) -> tuple[float, float]:
        lo = self.bounds[i - 1] if i > 0 else (
            self.vmin if self.vmin is not None else 0.0
        )
        hi = self.bounds[i] if i < len(self.bounds) else (
            self.vmax if self.vmax is not None else lo
        )
        return lo, hi

    def quantile(self, p: float) -> float:
        """Linear-interpolated quantile from the buckets, clamped to
        the observed [min, max] (exact for p=0/1 by construction)."""
        if not self.count:
            raise ValueError(f"quantile of empty histogram {self.name}")
        rank = p * (self.count - 1)
        cum = 0
        for i, c in enumerate(self.counts):
            if not c:
                continue
            if rank < cum + c:
                lo, hi = self._bucket_edges(i)
                frac = (rank - cum + 1.0) / c
                v = lo + (hi - lo) * min(1.0, max(0.0, frac))
                return min(max(v, self.vmin), self.vmax)
            cum += c
        return self.vmax  # p == 1 tail

    def quantiles(self, ps=(0.5, 0.95, 0.99)) -> dict[str, float]:
        """Same key format as ``bench/harness.py quantiles``."""
        return {f"p{100 * p:g}": self.quantile(p) for p in ps}

    def merge(self, other: "Histogram") -> "Histogram":
        """Bucket-wise add of ``other`` into self (associative and
        commutative over same-bounds histograms).  Returns self."""
        if other.bounds != self.bounds:
            raise ValueError(
                f"cannot merge {other.name} into {self.name}: "
                "bucket bounds differ"
            )
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.total += other.total
        if other.vmin is not None:
            self.vmin = other.vmin if self.vmin is None else min(
                self.vmin, other.vmin
            )
        if other.vmax is not None:
            self.vmax = other.vmax if self.vmax is None else max(
                self.vmax, other.vmax
            )
        return self

    @classmethod
    def merged(cls, *hs: "Histogram") -> "Histogram":
        """A fresh histogram holding the bucket-wise sum of ``hs``."""
        if not hs:
            raise ValueError("merged() of no histograms")
        out = cls(hs[0].name, hs[0].bounds)
        for h in hs:
            out.merge(h)
        return out

    def to_dict(self) -> dict:
        return {
            "bounds": list(self.bounds),
            "counts": list(self.counts),
            "count": self.count,
            "sum": self.total,
            "min": self.vmin,
            "max": self.vmax,
        }

    @classmethod
    def from_dict(cls, name: str, d: dict) -> "Histogram":
        h = cls(name, d["bounds"])
        counts = [int(c) for c in d["counts"]]
        if len(counts) != len(h.counts):
            raise ValueError(
                f"histogram {name}: {len(counts)} counts for "
                f"{len(h.bounds)} bounds"
            )
        h.counts = counts
        h.count = int(d["count"])
        h.total = float(d["sum"])
        h.vmin = d["min"]
        h.vmax = d["max"]
        return h


class MetricsRegistry:
    """Get-or-create home for named metrics; one per serve drain.

    The registry is the artifact surface: ``to_dict()`` is written as
    the versioned ``metrics`` block, ``from_dict`` reads one back
    (``tools/bench_compare.py`` diffs two of them).  Re-requesting a
    name returns the existing instance (so scheduler, pool, journal and
    faults can all hold references to the same series), and
    :meth:`attach` adopts a metric created before the registry existed
    — the pool's counters predate the scheduler that owns the run's
    registry.
    """

    def __init__(self):
        self.counters: dict[str, Counter] = {}
        self.gauges: dict[str, Gauge] = {}
        self.histograms: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def gauge(self, name: str) -> Gauge:
        g = self.gauges.get(name)
        if g is None:
            g = self.gauges[name] = Gauge(name)
        return g

    def histogram(self, name: str, bounds=LATENCY_BUCKETS_S) -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(name, bounds)
        elif tuple(float(b) for b in bounds) != h.bounds:
            raise ValueError(
                f"histogram {name} re-registered with different bounds"
            )
        return h

    def attach(self, metric) -> None:
        """Adopt an existing metric object under its own name (identity
        preserved: the owner keeps incrementing the same instance)."""
        table = {
            Counter: self.counters, Gauge: self.gauges,
            Histogram: self.histograms,
        }[type(metric)]
        table[metric.name] = metric

    def to_dict(self) -> dict:
        return {
            "version": METRICS_VERSION,
            "counters": {
                k: c.to_dict() for k, c in sorted(self.counters.items())
            },
            "gauges": {
                k: g.to_dict() for k, g in sorted(self.gauges.items())
            },
            "histograms": {
                k: h.to_dict()
                for k, h in sorted(self.histograms.items())
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "MetricsRegistry":
        ver = d.get("version")
        if ver != METRICS_VERSION:
            raise ValueError(
                f"metrics block version {ver!r} != {METRICS_VERSION}"
            )
        reg = cls()
        for k, v in d.get("counters", {}).items():
            reg.counters[k] = Counter(k, v)
        for k, v in d.get("gauges", {}).items():
            g = Gauge(k)
            g.value = v["value"]
            g.vmin, g.vmax = v["min"], v["max"]
            g.updates = int(v["updates"])
            reg.gauges[k] = g
        for k, v in d.get("histograms", {}).items():
            reg.histograms[k] = Histogram.from_dict(k, v)
        return reg
