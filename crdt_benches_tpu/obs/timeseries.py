"""Continuous serve telemetry: ring-buffered per-round time-series.

PR 6's registry answers "what did the whole drain look like" — one
aggregate per series, visible only after the run.  This module answers
"what is happening NOW, and when did it change": every macro-round the
scheduler hands :class:`TimeseriesRecorder` a sample (round latency,
occupancy, queue depth, cumulative counters), the recorder folds
``window_rounds`` consecutive rounds into one **window** (delta-encoded
against the cumulative counters, so each window stands alone), and the
closed windows live in a bounded ring — a million-round soak holds
``capacity`` windows, never a million samples.  Consumers:

- the artifact's versioned ``timeseries`` block (:meth:`block`);
- an optional JSONL stream file (``--serve-timeseries PATH``): one line
  per closed window, appended live, so an external tail follows the run;
- :mod:`crdt_benches_tpu.obs.anomaly` detectors (windows are their
  input);
- :mod:`crdt_benches_tpu.obs.status`'s ``/status.json`` + ``/metrics``
  (the facade publishes a fresh registry snapshot at every window
  close).

:class:`ServeTelemetry` is the facade the scheduler threads through the
drain: it fans one ``note_round`` out to the recorder, the per-shard
series (:mod:`obs.shard`), the anomaly detectors and the status server,
and re-bases per drain so a soak run (``--serve-soak``) accumulates one
continuous series across many fleet drains.

Hot-path discipline (enforced by graftlint G013): everything called per
round here is pure host arithmetic on pre-registered metric objects —
no registry get-or-create, no socket/server work, no device traffic.

Thread confinement (enforced by graftlint G014-G016 + the runtime race
sanitizer): both classes here are owned by the **hot** thread — the
recorder's ring, the delta baseline, and the facade's re-basing state
are never touched from another thread.  The only state that leaves the
hot thread is what :class:`ServeTelemetry` pushes through the status
server's declared publish points (fresh ``to_dict()`` / status-field
snapshots, never live objects); the status threads read those
snapshots, never the recorder.
"""

from __future__ import annotations

import json
import os
from collections import deque
from dataclasses import dataclass, field

#: Bump when the ``timeseries`` artifact block changes shape.
TIMESERIES_VERSION = 1

#: Cumulative counter keys a round sample carries (delta-encoded into
#: windows).  Fixed set: a window is self-describing in the artifact.
CUM_KEYS = (
    "ops", "unit_ops", "shed", "deferred", "quarantines", "dup_dropped",
    "evictions", "restores", "promotions", "recoveries",
    "journal_bytes", "fence_entries",
)


def read_rss_bytes() -> int | None:
    """Current resident set size, or None where /proc is unavailable.
    (``ru_maxrss`` is a high-water mark — useless for detecting that
    growth *stopped* — so the leak detector wants the live value.)"""
    try:
        with open("/proc/self/statm", encoding="ascii") as f:
            pages = int(f.read().split()[1])
    except (OSError, ValueError, IndexError):
        return None
    return pages * (os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf")
                    else 4096)


class TimeseriesRecorder:  # graftlint: thread=hot
    """Fold per-round samples into bounded, delta-encoded windows.

    One window = up to ``window_rounds`` macro-rounds: wall seconds,
    op/unit-op deltas, occupancy mean, queue-depth max, shed / defer /
    quarantine / eviction / journal-byte / fence-entry deltas, compile
    and barrier round counts, and (under a mesh) per-shard op / lane
    sums.  Closed windows land in a ring of ``capacity`` (oldest
    dropped, counted, never silently) and — when ``stream_path`` is set
    — are appended as one JSON line each.
    """

    def __init__(self, window_rounds: int = 8, capacity: int = 512,
                 stream_path: str | None = None):
        self.window_rounds = max(1, int(window_rounds))
        self.capacity = max(1, int(capacity))
        self.windows: deque[dict] = deque(maxlen=self.capacity)
        self.dropped = 0
        self.stream_path = stream_path
        self._stream = None
        self._cur: dict | None = None
        self._cum: dict[str, int] = {}
        self._index = 0  # windows ever closed (stable window ids)
        self.rounds_seen = 0
        self.drains = 0
        self.n_shards = 1

    # ---- drain lifecycle ----

    def rebase(self, n_shards: int = 1) -> None:
        """A new drain begins: its ServeStats counters restart at zero,
        so the delta baseline must too.  The window ring persists — a
        soak's series is continuous across drains."""
        self._cum = {}
        self.n_shards = max(1, int(n_shards))
        self.drains += 1

    # ---- per-round sampling (hot path: pure host arithmetic) ----

    def note_round(self, *, round_no: int, seconds: float, compiled: bool,
                   barrier: bool, occupancy: float, queue_depth: int,
                   cum: dict, shard_ops=None, shard_lanes=None,
                   shard_units=None) -> dict | None:
        """Fold one macro-round into the current window.  Returns the
        window dict if this round CLOSED it, else None."""
        self.rounds_seen += 1
        w = self._cur
        if w is None:
            w = self._cur = {
                "index": self._index,
                "drain": self.drains,
                "start_round": round_no,
                "rounds": 0,
                "seconds": 0.0,
                "occ_sum": 0.0,
                "queue_depth_max": 0,
                "compile_rounds": 0,
                "barrier_rounds": 0,
                "shard_ops": [0] * self.n_shards,
                "shard_unit_ops": [0] * self.n_shards,
                "shard_lanes": [0] * self.n_shards,
            }
            for k in CUM_KEYS:
                w[k] = 0
        w["end_round"] = round_no
        w["rounds"] += 1
        w["seconds"] += seconds
        w["occ_sum"] += occupancy
        if queue_depth > w["queue_depth_max"]:
            w["queue_depth_max"] = queue_depth
        if compiled:
            w["compile_rounds"] += 1
        if barrier:
            w["barrier_rounds"] += 1
        for k in CUM_KEYS:
            v = int(cum.get(k, 0))
            w[k] += v - self._cum.get(k, 0)
            self._cum[k] = v
        if shard_ops is not None:
            so, su, sl = (w["shard_ops"], w["shard_unit_ops"],
                          w["shard_lanes"])
            for s in range(min(self.n_shards, len(shard_ops))):
                so[s] += shard_ops[s]
                su[s] += shard_units[s]
                sl[s] += shard_lanes[s]
        if w["rounds"] >= self.window_rounds:
            return self._close()
        return None

    def close_partial(self) -> dict | None:
        """End of a drain: flush the in-progress window (marked
        ``full: false`` so rate detectors can skip it)."""
        if self._cur is None or self._cur["rounds"] == 0:
            self._cur = None
            return None
        return self._close()

    # ---- window close ----

    def _close(self) -> dict:
        w = self._cur
        self._cur = None
        self._index += 1
        rounds = w["rounds"]
        occ_sum = w.pop("occ_sum")
        w["occupancy"] = occ_sum / rounds
        w["lanes"] = sum(w["shard_lanes"])
        w["full"] = rounds >= self.window_rounds
        # throughput in unit ops (the elements/s analog) per wall second
        w["throughput"] = (
            w["unit_ops"] / w["seconds"] if w["seconds"] > 0 else 0.0
        )
        w["rss_bytes"] = read_rss_bytes()
        if len(self.windows) == self.windows.maxlen:
            self.dropped += 1
        self.windows.append(w)
        if self.stream_path:
            if self._stream is None:
                d = os.path.dirname(self.stream_path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._stream = open(self.stream_path, "w",
                                    encoding="utf-8")
            self._stream.write(json.dumps(w, separators=(",", ":")))
            self._stream.write("\n")
            self._stream.flush()
        return w

    # ---- artifact surface ----

    def block(self) -> dict:
        """The versioned ``timeseries`` artifact block (non-destructive:
        callable per soak iteration, the last call sees everything)."""
        return {
            "version": TIMESERIES_VERSION,
            "window_rounds": self.window_rounds,
            "n_shards": self.n_shards,
            "drains": self.drains,
            "rounds_seen": self.rounds_seen,
            "dropped_windows": self.dropped,
            "stream": self.stream_path,
            "windows": list(self.windows),
        }

    def close(self) -> None:
        if self._stream is not None:
            self._stream.close()
            self._stream = None


@dataclass
class ServeTelemetry:  # graftlint: thread=hot
    """The continuous-telemetry bundle one serve run threads through
    its scheduler(s).  Any piece may be None; a soak run shares one
    bundle across every drain it spins up."""

    recorder: TimeseriesRecorder | None = None
    anomaly: object | None = None  # obs/anomaly.py AnomalyDetector
    status: object | None = None  # obs/status.py StatusServer
    flight: object | None = None  # obs/flight.py FlightRecorder
    shards: object | None = field(default=None, init=False)
    registry: object | None = field(default=None, init=False)
    reqtrace: object | None = field(default=None, init=False)
    _flight_fired_seen: int = field(default=0, init=False)
    _drain_done: bool = field(default=False, init=False)

    def bind(self, pool, registry, reqtrace=None) -> None:
        """A drain's scheduler calls this once at construction: build
        the per-shard series against the drain's registry, re-base the
        recorder's delta baseline, and publish an initial snapshot so
        a scrape BEFORE the first window close already answers.
        ``reqtrace`` is the drain's RequestTracker — the flight
        recorder dumps its sampled/in-flight traces on a trigger."""
        from .shard import ShardMetrics

        self.registry = registry
        self.shards = ShardMetrics(pool, registry)
        self.reqtrace = reqtrace
        self._drain_done = False
        if self.recorder is not None:
            self.recorder.rebase(n_shards=pool.n_sh)
        if self.status is not None:
            self.status.publish_metrics(registry.to_dict())
            self.status.publish_status({"phase": "starting", "rounds": 0})

    def _flight_requests(self) -> list:
        if self.reqtrace is None:
            return []
        return self.reqtrace.dump_requests()

    def flight_dump(self, reason: str, status: dict | None = None) -> None:
        """Trigger a flight-recorder dump with everything the bundle
        holds (no-op without a recorder)."""
        if self.flight is None:
            return
        self.flight.trigger(
            reason,
            registry=self.registry,
            status=status,
            requests=self._flight_requests(),
            anomalies=(
                self.anomaly.active_kinds()
                if self.anomaly is not None else []
            ),
        )

    # -- per-round fan-out (hot path; pre-registered objects only) --

    def note_round(self, *, round_no: int, seconds: float, compiled: bool,
                   barrier: bool, occupancy: float, queue_depth: int,
                   cum: dict, shard_lanes, shard_ops, shard_units,
                   status: dict) -> None:
        if self.shards is not None:
            self.shards.note_round(shard_lanes, shard_ops, shard_units)
        closed = None
        if self.recorder is not None:
            closed = self.recorder.note_round(
                round_no=round_no, seconds=seconds, compiled=compiled,
                barrier=barrier, occupancy=occupancy,
                queue_depth=queue_depth, cum=cum, shard_ops=shard_ops,
                shard_lanes=shard_lanes, shard_units=shard_units,
            )
        if self.anomaly is not None:
            self.anomaly.note_round(
                seconds, skip=compiled or barrier, round_no=round_no
            )
        if self.flight is not None:
            # one small dict per round into the bounded ring; a NEW
            # anomaly fire triggers the atomic dump (the post-mortem
            # window this recorder exists to keep)
            self.flight.note_round({
                "round": round_no,
                "seconds": seconds,
                "compiled": compiled,
                "barrier": barrier,
                "occupancy": occupancy,
                "queue_depth": queue_depth,
                "ops": cum.get("ops", 0),
                "shed": cum.get("shed", 0),
                "deferred": cum.get("deferred", 0),
                "quarantines": cum.get("quarantines", 0),
                "recoveries": cum.get("recoveries", 0),
            })
        if closed is not None:
            if self.anomaly is not None:
                self.anomaly.note_window(closed)
            if self.shards is not None:
                self.shards.sample_memory()
            if self.status is not None and self.registry is not None:
                self.status.publish_metrics(self.registry.to_dict())
        if self.status is not None:
            if self.anomaly is not None:
                status["anomalies_active"] = self.anomaly.active_kinds()
                self.status.set_health(
                    not status["anomalies_active"],
                    ",".join(status["anomalies_active"]),
                )
            self.status.publish_status(status)
        # flight trigger LAST, after both the per-round and per-window
        # detectors had their look: a NEW fire (per-round watchdog OR
        # window-level degradation/leak) dumps the post-mortem window
        if (self.flight is not None and self.anomaly is not None
                and self.anomaly.fired > self._flight_fired_seen):
            self._flight_fired_seen = self.anomaly.fired
            self.flight_dump(
                "anomaly:" + ",".join(self.anomaly.active_kinds()),
                status=status,
            )

    def note_event(self, kind: str, **fields) -> None:
        """Durability/recovery lifecycle marker (snapshot barrier,
        compaction pass, in-run recovery): lands in the flight
        recorder's event ring so a post-mortem dump says when the
        subsystem last acted.  Hot-thread only; pure host append."""
        if self.flight is not None:
            self.flight.note_event(kind, **fields)

    def note_phase(self, phase: str) -> None:
        """Driver-side heartbeat between drains (fleet build, verify):
        no round is running, but the publisher is alive — resets the
        status server's staleness clock."""
        if self.status is not None:
            self.status.publish_status({"phase": phase})

    def publish_metrics_now(self) -> None:
        """Out-of-window registry publish for rare operator-visible
        state transitions (a reshard begin/resume/commit).  The normal
        cadence publishes only at window closes — a migration that
        begins AND commits inside one window would never render on
        /metrics while in flight without this."""
        if self.status is not None and self.registry is not None:
            self.status.publish_metrics(self.registry.to_dict())

    # -- drain end (driver side, off the hot path) --

    def drain_end(self, status: dict | None = None) -> None:
        """Close the partial window, push it through the detectors, and
        publish the final snapshots.  Idempotent per drain."""
        if self._drain_done:
            return
        self._drain_done = True
        if self.recorder is not None:
            tail = self.recorder.close_partial()
            if tail is not None and self.anomaly is not None:
                self.anomaly.note_window(tail)
        if self.shards is not None:
            self.shards.sample_memory()
        if self.status is not None and self.registry is not None:
            self.status.publish_metrics(self.registry.to_dict())
        if self.status is not None and status is not None:
            if self.anomaly is not None:
                status["anomalies_active"] = self.anomaly.active_kinds()
            self.status.publish_status(status)
        if (self.flight is not None and self.anomaly is not None
                and self.anomaly.uncleared > 0):
            # an anomaly still ACTIVE at drain end fails the run — the
            # dump is the post-mortem that exit code used to discard
            self.flight_dump(
                "drain_end_active_anomaly:"
                + ",".join(self.anomaly.active_kinds()),
                status=status,
            )

    def close(self) -> None:
        """Release owned resources (stream file, status server)."""
        if self.recorder is not None:
            self.recorder.close()
        if self.status is not None:
            self.status.stop()
