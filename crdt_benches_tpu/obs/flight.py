"""Anomaly flight recorder: a bounded ring dumped atomically on fire.

A soak failure today leaves an exit code and whatever the artifact
recorded *after* the drain; the window that actually explains the
failure — the rounds right before the anomaly — is gone.  The
:class:`FlightRecorder` keeps exactly that window in memory:

- a ring of the last ``ring`` per-round event samples (round number,
  wall seconds, occupancy, queue depth, compile/barrier flags, fault
  counters — the ``obs/timeseries.py`` sample vocabulary, pre-window
  granularity);
- the last N sampled request traces from ``obs/reqtrace.py`` (plus
  every still-open request at dump time — the in-flight set is what a
  crash post-mortem wants);
- the full metric-registry snapshot and the latest status fields.

On a trigger — anomaly fire (``obs/anomaly.py`` via the telemetry
facade), an unrecovered fault at drain end, or a crash escaping the
drain — the whole picture is dumped as ONE JSON document, written
atomically (tmp + ``os.replace``): a reader never sees a half dump, and
a repeated trigger replaces the file with a fresh, more complete one
(``dump_index`` says which trigger wrote it; every reason is retained).

The module doubles as the dump validator the chaos smoke gates on::

    python -m crdt_benches_tpu.obs.flight bench_results/..._flight.json

exits nonzero unless the file is a schema-valid flight dump.

Lifecycle discipline (graftlint G013): the recorder is CONSTRUCTED by
the bench driver, never on the hot path; the hot path only appends to
the ring and — rarely, on an anomaly trigger — writes the dump (a
post-mortem beats purity exactly once, when the run is already sick).
Thread confinement: owned by the **hot** thread end to end.
"""

from __future__ import annotations

import json
import os
import sys
import time
from collections import deque

from ..lint.fs_sanitizer import fs_protocol
from ..lint.sanitizer import fenced
from ..utils.fsdur import fsync_dir as _fsync_dir

#: Bump when the dump document changes shape.
FLIGHT_VERSION = 1

#: Default per-round event ring depth.
DEFAULT_RING = 256


class FlightRecorder:  # graftlint: thread=hot
    """Bounded pre-anomaly window + atomic dump (module docstring)."""

    def __init__(self, path: str, ring: int = DEFAULT_RING,
                 event_ring: int = 64):
        self.path = path
        self.rounds: deque[dict] = deque(maxlen=max(1, int(ring)))
        self.events: deque[dict] = deque(maxlen=max(1, int(event_ring)))
        self.rounds_seen = 0
        self.events_seen = 0
        self.dumps = 0
        self.dump_failures = 0
        self.last_error: str | None = None
        self.reasons: list[str] = []

    # ---- hot path: one small dict append per macro-round ----

    def note_round(self, sample: dict) -> None:
        self.rounds_seen += 1
        self.rounds.append(sample)

    def note_event(self, kind: str, **fields) -> None:
        """Record a durability/recovery lifecycle event (snapshot
        barrier committed, WAL compaction pass, in-run recovery) into
        its own bounded ring — the post-mortem wants 'when did the
        subsystem last act', which round samples alone cannot answer."""
        self.events_seen += 1
        self.events.append({"kind": str(kind), **fields})

    # ---- triggers (anomaly fire / unrecovered fault / crash) ----

    @fenced
    def trigger(self, reason: str, *, registry=None, status=None,  # graftlint: fence=flight  # graftlint: durable=flight
                requests=None, anomalies=None) -> str:
        """Dump the recorder's state atomically and return the path.
        Later triggers replace the file (each dump is a superset-in-
        time of the last; ``reasons`` accumulates).

        A declared ``fence=flight`` sync boundary: the dump is host
        JSON + file I/O that runs exactly when the drain is already
        sick (anomaly fire / unrecovered fault / crash) — the one
        place a post-mortem beats hot-path purity.  The fence entry
        lands in ``boundary_syncs`` like every other crossing, so a
        run that dumped says so in its own artifact — and G011
        dead-checks this fence only against artifacts whose
        ``boundary_syncs.flight`` records a dump (a chaos run whose
        faults all recover never enters it; ``fence=chaos`` would
        false-positive there).

        BEST-EFFORT by contract: a dump that cannot be written (typo'd
        path, full disk, unserializable snapshot) must never kill a
        run the anomaly would have cleared, nor — on the crash path —
        replace the exception it is documenting.  Failures are counted
        (``dump_failures`` / ``last_error``, surfaced in the
        artifact's ``flight`` block) and the chaos smoke's validator
        gate catches a silently-missing dump."""
        self.reasons.append(str(reason))
        doc = {
            "version": FLIGHT_VERSION,
            "reason": str(reason),
            "reasons": list(self.reasons),
            "dump_index": self.dumps + 1,
            "time_unix": time.time(),
            "rounds_seen": self.rounds_seen,
            "rounds": list(self.rounds),
            "events_seen": self.events_seen,
            "events": list(self.events),
            "requests": list(requests) if requests else [],
            "metrics": registry.to_dict() if registry is not None
            else None,
            "status": dict(status) if status else None,
            "anomalies": list(anomalies) if anomalies else [],
        }
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = self.path + ".tmp"
            with fs_protocol("flight"):
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(doc, f, separators=(",", ":"))
                    # a post-mortem that evaporates with the page cache
                    # explains nothing: fsync before the commit rename,
                    # and the directory entry after (G018)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)  # commit: never half a dump
                if d:
                    _fsync_dir(d)
        except (OSError, TypeError, ValueError) as e:
            self.dump_failures += 1
            self.last_error = f"{type(e).__name__}: {e}"
            try:  # a half-written .tmp must not outlive the failure
                os.unlink(self.path + ".tmp")
            except OSError:
                pass
            return self.path
        self.dumps += 1
        return self.path

    def summary(self) -> dict:
        """The artifact's ``flight`` block: where the dump lives and
        why it was (or was not) written."""
        return {
            "path": self.path,
            "ring": self.rounds.maxlen,
            "rounds_seen": self.rounds_seen,
            "events_seen": self.events_seen,
            "dumps": self.dumps,
            "dump_failures": self.dump_failures,
            "last_error": self.last_error,
            "reasons": list(self.reasons),
        }


# ---------------------------------------------------------------------------
# schema validation (the chaos smoke gates on this)
# ---------------------------------------------------------------------------


def validate_flight(data) -> list[str]:
    """Structural checks on one flight dump.  Returns problems (empty
    = valid)."""
    errors: list[str] = []
    if not isinstance(data, dict):
        return ["top level must be an object"]
    if data.get("version") != FLIGHT_VERSION:
        errors.append(
            f"version {data.get('version')!r} != {FLIGHT_VERSION}"
        )
    if not data.get("reason") or not isinstance(data["reason"], str):
        errors.append("reason must be a non-empty string")
    if not isinstance(data.get("dump_index"), int) or \
            data.get("dump_index", 0) < 1:
        errors.append("dump_index must be a positive integer")
    rounds = data.get("rounds")
    if not isinstance(rounds, list):
        errors.append("rounds must be a list")
        rounds = []
    if not rounds:
        errors.append("rounds is empty — the recorder saw no round "
                      "before the trigger")
    for i, r in enumerate(rounds):
        if not isinstance(r, dict):
            errors.append(f"rounds[{i}]: not an object")
            continue
        if not isinstance(r.get("round"), int):
            errors.append(f"rounds[{i}]: missing integer 'round'")
        if not isinstance(r.get("seconds"), (int, float)):
            errors.append(f"rounds[{i}]: missing numeric 'seconds'")
    reqs = data.get("requests")
    if not isinstance(reqs, list):
        errors.append("requests must be a list")
        reqs = []
    for i, r in enumerate(reqs):
        if not isinstance(r, dict) or "doc" not in r:
            errors.append(f"requests[{i}]: not a request trace (no "
                          "'doc')")
    events = data.get("events", [])
    if not isinstance(events, list):
        errors.append("events must be a list")
        events = []
    for i, e in enumerate(events):
        if not isinstance(e, dict) or not isinstance(e.get("kind"), str):
            errors.append(f"events[{i}]: not an event (no 'kind')")
    m = data.get("metrics")
    if m is not None and not (
        isinstance(m, dict) and isinstance(m.get("version"), int)
    ):
        errors.append("metrics must be null or a versioned registry "
                      "snapshot")
    if not isinstance(data.get("anomalies"), list):
        errors.append("anomalies must be a list")
    return errors


def validate_flight_file(path: str) -> list[str]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable flight dump: {e}"]
    return validate_flight(data)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m crdt_benches_tpu.obs.flight DUMP.json",
              file=sys.stderr)
        return 2
    errors = validate_flight_file(argv[0])
    for e in errors:
        print(f"{argv[0]}: {e}", file=sys.stderr)
    if not errors:
        with open(argv[0], encoding="utf-8") as f:
            d = json.load(f)
        print(
            f"{argv[0]}: valid flight dump — reason {d['reason']!r}, "
            f"{len(d['rounds'])} rounds, {len(d['requests'])} request "
            f"traces, dump {d['dump_index']}"
        )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
