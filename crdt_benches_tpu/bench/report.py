"""HTML report + distribution plots from saved bench results — the
capability analog of Criterion's ``target/criterion`` report output
(reference Cargo.toml:11 pulls criterion, whose generated main writes
per-bench HTML reports and sample-distribution plots; this was the one
measurement capability the rebuild had not re-provided, VERDICT r4
"missing" #1).

Reads the runner's ``bench_results/*.json`` artifacts (bench/harness.py
save_results format) and writes a single self-contained HTML file: one
summary table per group plus an inline-SVG sample-distribution strip
(every sample as a tick, median marked) per cell.  No plotting
dependency — the SVG is hand-emitted.

Usage:
  python -m crdt_benches_tpu.bench.report [results.json ...] [-o out.html]

With no inputs, every ``bench_results/*.json`` with a ``results`` list is
included.
"""

from __future__ import annotations

import argparse
import glob
import html
import json
import os


def _fmt(n: float) -> str:
    if n >= 1e6:
        return f"{n/1e6:,.1f}M"
    if n >= 1e3:
        return f"{n/1e3:,.0f}k"
    return f"{n:,.0f}"


def _strip_svg(times: list[float], width: int = 220, h: int = 26) -> str:
    """Sample-distribution strip: one tick per sample on a linear time
    axis spanning [min, max], median in a second color."""
    if not times:
        return ""
    lo, hi = min(times), max(times)
    span = (hi - lo) or 1e-12
    x = lambda t: 6 + (width - 12) * (t - lo) / span
    med = sorted(times)[len(times) // 2]
    ticks = "".join(
        f'<line x1="{x(t):.1f}" y1="4" x2="{x(t):.1f}" y2="{h-10}" '
        f'stroke="#4878d0" stroke-width="1.5"/>'
        for t in times
    )
    return (
        f'<svg width="{width}" height="{h}" role="img">'
        f'<line x1="6" y1="{h-8}" x2="{width-6}" y2="{h-8}" '
        f'stroke="#999" stroke-width="1"/>'
        f"{ticks}"
        f'<line x1="{x(med):.1f}" y1="2" x2="{x(med):.1f}" y2="{h-8}" '
        f'stroke="#d65f5f" stroke-width="2.5"/>'
        f"</svg>"
    )


def load_results(paths: list[str]) -> list[dict]:
    rows = []
    for p in paths:
        try:
            data = json.load(open(p))
        except (OSError, json.JSONDecodeError):
            continue
        # save_results writes a flat LIST of cell dicts (bench/harness.py)
        cells = data if isinstance(data, list) else data.get("results", [])
        for r in cells:
            if not isinstance(r, dict) or "group" not in r:
                continue
            r = dict(r)
            r["_source"] = os.path.basename(p)
            rows.append(r)
    return rows


def render(rows: list[dict]) -> str:
    groups: dict[str, list[dict]] = {}
    for r in rows:
        groups.setdefault(r.get("group", "?"), []).append(r)
    parts = [
        "<!doctype html><meta charset='utf-8'>",
        "<title>crdt_benches_tpu report</title>",
        "<style>body{font:14px system-ui;margin:2em;max-width:70em}"
        "table{border-collapse:collapse;margin:1em 0}"
        "td,th{border:1px solid #ccc;padding:4px 10px;text-align:right}"
        "th{background:#f3f3f3}td.l,th.l{text-align:left}"
        "caption{font-weight:600;text-align:left;padding:4px 0}</style>",
        "<h1>crdt_benches_tpu — bench report</h1>",
        "<p>element = one trace patch (the reference's Criterion "
        "throughput unit, src/main.rs:25); strip = per-sample times, "
        "red line = median.</p>",
    ]
    for group in sorted(groups):
        parts.append(
            f"<table><caption>{html.escape(group)}</caption>"
            "<tr><th class='l'>trace/config</th><th class='l'>backend</th>"
            "<th>median el/s</th><th>median s</th><th>min s</th>"
            "<th>max s</th><th>n</th><th class='l'>samples</th>"
            "<th class='l'>source</th></tr>"
        )
        for r in sorted(
            groups[group],
            key=lambda r: (r.get("trace", ""), r.get("backend", "")),
        ):
            times = r.get("samples", r.get("times", []))
            med = sorted(times)[len(times) // 2] if times else 0.0
            elements = r.get("elements", 0)
            reps = r.get("replicas", 1) or 1
            # prefer the harness's own aggregate figure when present
            eps = r.get(
                "elements_per_sec", elements * reps / med if med else 0.0
            )
            stats = (
                f"<td>{med:.4f}</td><td>{min(times):.4f}</td>"
                f"<td>{max(times):.4f}</td><td>{len(times)}</td>"
                f"<td class='l'>{_strip_svg(times)}</td>"
                if times
                else "<td></td><td></td><td></td><td>0</td><td></td>"
            )
            parts.append(
                "<tr>"
                f"<td class='l'>{html.escape(str(r.get('trace', '')))}</td>"
                f"<td class='l'>{html.escape(str(r.get('backend', '')))}</td>"
                f"<td>{_fmt(eps)}</td>"
                f"{stats}"
                f"<td class='l'>{html.escape(r.get('_source', ''))}</td>"
                "</tr>"
            )
        parts.append("</table>")
    return "".join(parts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("inputs", nargs="*", help="results JSON files")
    ap.add_argument("-o", "--out", default="bench_results/report.html")
    args = ap.parse_args(argv)
    paths = args.inputs or sorted(glob.glob("bench_results/*.json"))
    rows = load_results(paths)
    if not rows:
        print("no results found")
        return 1
    html_text = render(rows)
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        f.write(html_text)
    print(f"wrote {args.out}: {len(rows)} cells from {len(paths)} files")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
