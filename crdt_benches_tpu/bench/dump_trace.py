"""Dump a trace as the flat binary format consumed by native/bench_native.

Usage: python -m crdt_benches_tpu.bench.dump_trace <trace-name> [out.bin]
"""

from __future__ import annotations

import sys

import numpy as np

from ..traces.loader import load_testing_data
from ..traces.patches import patch_arrays


def dump(name: str, out_path: str | None = None) -> str:
    trace = load_testing_data(name)
    pa = patch_arrays(trace)
    out_path = out_path or f"/tmp/{name}.bin"
    with open(out_path, "wb") as f:
        np.asarray([pa.n_patches, len(pa.init), len(pa.ins_flat)], np.int64).tofile(f)
        pa.pos.tofile(f)
        pa.del_count.tofile(f)
        pa.ins_off.tofile(f)
        pa.ins_flat.tofile(f)
        pa.init.tofile(f)
    return out_path


if __name__ == "__main__":
    name = sys.argv[1] if len(sys.argv) > 1 else "automerge-paper"
    out = dump(name, sys.argv[2] if len(sys.argv) > 2 else None)
    print(out)
