"""Bench matrix runner — the harness entry point (the capability of the
reference's Criterion main, reference src/main.rs:17-85), configurable
instead of hardcoded (SURVEY.md section 5 "config system": the trace list and
backend matrix were consts/commented code at src/main.rs:10-15,43-46,76-79).

Groups:
  upstream    — local-edit replay throughput per (trace x backend)
  downstream  — remote-update-apply throughput per (trace x backend)

Usage:
  python -m crdt_benches_tpu.bench.runner --traces sveltecomponent \
      --backends cpp-rope,cpp-crdt,jax --replicas 8 --samples 5 \
      [--save-baseline NAME] [--baseline NAME] [--filter upstream]
"""

from __future__ import annotations

import argparse
import sys

from ..traces.loader import TRACES, load_testing_data
from ..traces.patches import patch_arrays
from ..backends.base import upstream_backends
from .harness import (
    BenchResult,
    compare_to_baseline,
    markdown_table,
    measure,
    save_results,
)


def run_upstream(trace_name: str, backend: str, samples: int, warmup: int,
                 replicas: int, batch: int,
                 profile_dir: str | None = None) -> BenchResult | None:
    trace = load_testing_data(trace_name)
    elements = len(trace)
    native_names = _native_upstreams()
    if backend in native_names:
        from ..backends.native import native_available

        if not native_available():
            return None
        cls = native_names[backend]
        if getattr(cls, "EDITS_USE_BYTE_OFFSETS", False):
            # byte-addressed backend: rewrite offsets to UTF-8 byte units
            # (reference src/main.rs:21-23)
            pa = patch_arrays(trace.chars_to_bytes(), bytes_mode=True)
        else:
            pa = patch_arrays(trace)
        end_len = pa.end_len

        def iter_fn():
            n = cls.replay_patches(pa)
            assert n == end_len, f"{backend}: {n} != {end_len}"

        times = measure(iter_fn, warmup=warmup, samples=samples,
                        min_sample_time=0.05)
        return BenchResult("upstream", trace_name, backend, elements, times)
    if backend == "python-oracle":
        from ..oracle import OracleDocument

        def iter_fn():
            doc = OracleDocument.from_str(trace.start_content)
            for pos, d, ins in trace.iter_patches():
                doc.replace(pos, pos + d, ins)
            assert len(doc) == len(trace.end_content)

        times = measure(iter_fn, warmup=0, samples=max(2, samples // 2))
        return BenchResult("upstream", trace_name, backend, elements, times)
    if backend == "jax":
        try:
            from ..backends.jax_backend import JaxReplayBackend
        except ImportError:
            return None

        b = JaxReplayBackend(n_replicas=replicas, batch=batch)
        b.prepare(trace)
        times = measure(b.replay_once, warmup=warmup, samples=samples)
        if profile_dir:
            import jax

            with jax.profiler.trace(profile_dir):
                b.replay_once()
        return BenchResult(
            "upstream", trace_name, b.NAME, elements, times, replicas=replicas
        )
    if backend == "jax-pos":
        return None  # downstream-only variant
    raise ValueError(f"unknown backend {backend!r}")


def _native_upstreams() -> dict[str, type]:
    """Registered Upstream backends with a native whole-replay path
    (@register_upstream in backends/native.py populates the registry)."""
    try:
        from ..backends import native  # noqa: F401  (triggers registration)
    except OSError:
        pass
    return {
        name: cls
        for name, cls in upstream_backends().items()
        if hasattr(cls, "replay_patches")
    }


def run_downstream(trace_name: str, backend: str, samples: int,
                   warmup: int, replicas: int = 1,
                   batch: int = 256) -> BenchResult | None:
    trace = load_testing_data(trace_name)
    elements = len(trace)
    if backend == "cpp-crdt":
        from ..backends.native import CppCrdtDownstream, native_available

        if not native_available():
            return None
        down, _updates = CppCrdtDownstream.upstream_updates(trace)  # untimed
        end_len = len(trace.end_content)

        def iter_fn():
            n = down.apply_all_native()
            assert n == end_len

        times = measure(iter_fn, warmup=warmup, samples=samples,
                        min_sample_time=0.05)
        return BenchResult("downstream", trace_name, backend, elements, times)
    if backend in ("jax", "jax-pos"):
        try:
            from ..engine.downstream import JaxDownstreamBackend
        except ImportError:
            return None
        b = JaxDownstreamBackend(
            n_replicas=replicas, batch=batch,
            engine="v3" if backend == "jax-pos" else None,
        )
        b.prepare(trace)
        times = measure(b.replay_once, warmup=warmup, samples=samples)
        return BenchResult(
            "downstream", trace_name, b.NAME, elements, times,
            replicas=replicas,
        )
    return None


import functools


@functools.lru_cache(maxsize=8)
def _oracle_content(trace_name: str) -> str:
    """Oracle replay once per trace (it is a full per-op Python replay —
    shared across the (group x backend) verify cells)."""
    from ..oracle.text_oracle import replay_trace

    trace = load_testing_data(trace_name)
    want = replay_trace(trace)
    assert want == trace.end_content, "oracle self-check failed"
    return want


def verify_upstream(trace_name: str, backend: str, replicas: int,
                    batch: int) -> bool | None:
    """Byte-identity check for one upstream cell: decode the backend's
    final document and compare against the pure-Python oracle AND the
    trace's endContent (upgrading the reference's length-only assert,
    src/main.rs:35).  Returns None if the backend is unavailable."""
    trace = load_testing_data(trace_name)
    want = _oracle_content(trace_name)
    native_names = _native_upstreams()
    if backend in native_names:
        from ..backends.native import native_available

        if not native_available():
            return None
        cls = native_names[backend]
        if getattr(cls, "EDITS_USE_BYTE_OFFSETS", False):
            pa = patch_arrays(trace.chars_to_bytes(), bytes_mode=True)
        else:
            pa = patch_arrays(trace)
        if hasattr(cls, "replay_patches_content"):
            got = cls.replay_patches_content(pa)
        else:
            doc = cls.from_str(trace.start_content)
            t = (
                trace.chars_to_bytes()
                if getattr(cls, "EDITS_USE_BYTE_OFFSETS", False)
                else trace
            )
            for pos, d, ins in t.iter_patches():
                if d:
                    doc.remove(pos, pos + d)
                if ins:
                    doc.insert(pos, ins)
            got = doc.content()
        return got == want
    if backend == "python-oracle":
        return True  # the oracle is the reference point
    if backend == "jax":
        try:
            from ..backends.jax_backend import JaxReplayBackend
        except ImportError:
            return None

        b = JaxReplayBackend(n_replicas=replicas, batch=batch)
        b.prepare(trace)
        return b.final_content() == want
    return None


def verify_downstream(trace_name: str, backend: str, replicas: int,
                      batch: int) -> bool | None:
    trace = load_testing_data(trace_name)
    want = _oracle_content(trace_name)
    if backend == "cpp-crdt":
        from ..backends.native import CppCrdtDownstream, native_available

        if not native_available():
            return None
        down, _ = CppCrdtDownstream.upstream_updates(trace)
        down.apply_all_native()
        return down.content() == want
    if backend in ("jax", "jax-pos"):
        try:
            from ..engine.downstream import JaxDownstreamBackend
        except ImportError:
            return None
        b = JaxDownstreamBackend(
            n_replicas=replicas, batch=batch,
            engine="v3" if backend == "jax-pos" else None,
        )
        b.prepare(trace)
        return b.final_content() == want
    return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--traces", default=",".join(TRACES))
    ap.add_argument("--backends", default="cpp-rope,cpp-crdt,jax")
    ap.add_argument("--filter", default="", help="substring filter on group")
    ap.add_argument("--samples", type=int, default=5)
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--save-baseline", default=None)
    ap.add_argument("--baseline", default=None)
    ap.add_argument(
        "--profile", default=None, metavar="DIR",
        help="capture a jax.profiler trace of one jax-backend iteration "
             "into DIR (the tracing capability Criterion leaves to external "
             "tools; view with TensorBoard/XProf)",
    )
    ap.add_argument(
        "--verify", action="store_true",
        help="byte-compare every (group x trace x backend) cell's final "
             "document against the pure-Python oracle (upgrades the "
             "reference's length-only assert, src/main.rs:35,68); exits "
             "nonzero on any mismatch",
    )
    ap.add_argument(
        "--verify-only", action="store_true",
        help="run --verify checks without timing anything",
    )
    args = ap.parse_args(argv)

    if args.verify or args.verify_only:
        failures = []
        for trace in args.traces.split(","):
            for backend in args.backends.split(","):
                for group, fn in (
                    ("upstream", verify_upstream),
                    ("downstream", verify_downstream),
                ):
                    if args.filter and args.filter not in group:
                        continue
                    ok = fn(trace, backend, args.replicas, args.batch)
                    if ok is None:
                        continue
                    tag = "ok" if ok else "MISMATCH"
                    print(
                        f"verify {group}/{trace}/{backend}: {tag}",
                        file=sys.stderr,
                    )
                    if not ok:
                        failures.append((group, trace, backend))
        if failures:
            print(f"verify FAILED: {failures}", file=sys.stderr)
            return 1
        if args.verify_only:
            print("verify: all cells byte-identical", file=sys.stderr)
            return 0

    results: list[BenchResult] = []
    for trace in args.traces.split(","):
        for backend in args.backends.split(","):
            if not args.filter or args.filter in "upstream":
                r = run_upstream(trace, backend, args.samples, args.warmup,
                                 args.replicas, args.batch,
                                 profile_dir=args.profile)
                if r:
                    results.append(r)
                    print(
                        f"upstream/{trace}/{r.backend}: median "
                        f"{r.median * 1e3:.2f}ms -> {r.elements_per_sec:,.0f} el/s",
                        file=sys.stderr,
                    )
            if backend in ("cpp-crdt", "jax", "jax-pos") and (
                not args.filter or args.filter in "downstream"
            ):
                r = run_downstream(trace, backend, args.samples, args.warmup,
                                   replicas=args.replicas, batch=args.batch)
                if r:
                    results.append(r)
                    print(
                        f"downstream/{trace}/{r.backend}: median "
                        f"{r.median * 1e3:.2f}ms -> {r.elements_per_sec:,.0f} el/s",
                        file=sys.stderr,
                    )

    print(markdown_table(results))
    save_results(results, "latest")
    if args.save_baseline:
        save_results(results, args.save_baseline)
    if args.baseline:
        print("\n".join(compare_to_baseline(results, args.baseline)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
