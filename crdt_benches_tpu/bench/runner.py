"""Bench matrix runner — the harness entry point (the capability of the
reference's Criterion main, reference src/main.rs:17-85), configurable
instead of hardcoded (SURVEY.md section 5 "config system": the trace list and
backend matrix were consts/commented code at src/main.rs:10-15,43-46,76-79).

Groups:
  upstream    — local-edit replay throughput per (trace x backend)
  downstream  — remote-update-apply throughput per (trace x backend)

Usage:
  python -m crdt_benches_tpu.bench.runner --traces sveltecomponent \
      --backends cpp-rope,cpp-crdt,jax --replicas 8 --samples 5 \
      [--save-baseline NAME] [--baseline NAME] [--filter upstream]

Families:
  classic (default) — the per-trace replay matrix above
  serve             — the multi-tenant document-fleet engine (serve/):
      python -m crdt_benches_tpu.bench.runner --family serve \
          --serve-docs 4096 --serve-mix mixed --serve-mesh 8 \
          --serve-macro 8
      Bench ids are serve/<mix>/<fleet-size>; the run drains the fleet
      through K-deep macro-round dispatches (--serve-macro) of RLE-
      coalesced range ops, reports fleet patches/sec + steady-state
      p50/p95/p99 batch latency (compile rounds excluded, compile_time
      separate) + pad_fraction/coalesce_ratio, byte-verifies a
      per-capacity-class doc sample against the oracle, and writes
      bench_results/serve_<mix>_<docs>.json.

      Fault tolerance: --serve-journal DIR|auto enables the write-ahead
      op journal + snapshot barriers (--serve-snapshot-every);
      --serve-faults SPEC runs a seeded chaos drain (serve/faults.py
      grammar, e.g. "seed=7,spool_corrupt=1,device_loss=1,
      queue_overflow=1") with recovery metrics (MTTR in rounds, ops
      replayed/shed, quarantines) in the artifact; --serve-queue-cap
      bounds per-doc pending ops with --serve-overflow-policy deciding
      defer-vs-shed at the cap.  Chaos exit code is nonzero when the
      verify fails OR any injected fault goes unfired/unrecovered.

      Observability: --serve-trace PATH arms the obs/trace.py span
      tracer (Perfetto-loadable Chrome trace JSON with fence-crossing
      instants); --serve-profile N embeds a jax.profiler top-ops table
      of N steady rounds in the artifact's profile block; the artifact
      always carries the versioned typed-metrics block (obs/metrics.py)
      and per-doc admission-to-drain latency histograms by cause tag.
      tools/bench_compare.py diffs an artifact against the committed
      baseline (bench_results/serve_baseline.json) as the regression
      gate.
"""

from __future__ import annotations

import argparse
import sys

from ..traces.loader import TRACES, load_testing_data
from ..traces.patches import patch_arrays
from ..backends.base import upstream_backends
from .harness import (
    BenchResult,
    compare_to_baseline,
    markdown_table,
    measure,
    save_results,
)


def run_upstream(trace_name: str, backend: str, samples: int, warmup: int,
                 replicas: int, batch: int,
                 profile_dir: str | None = None) -> BenchResult | None:
    trace = load_testing_data(trace_name)
    elements = len(trace)
    native_names = _native_upstreams()
    if backend in native_names:
        from ..backends.native import native_available

        if not native_available():
            return None
        cls = native_names[backend]
        if getattr(cls, "EDITS_USE_BYTE_OFFSETS", False):
            # byte-addressed backend: rewrite offsets to UTF-8 byte units
            # (reference src/main.rs:21-23)
            pa = patch_arrays(trace.chars_to_bytes(), bytes_mode=True)
        else:
            pa = patch_arrays(trace)
        end_len = pa.end_len

        def iter_fn():
            n = cls.replay_patches(pa)
            assert n == end_len, f"{backend}: {n} != {end_len}"

        times = measure(iter_fn, warmup=warmup, samples=samples,
                        min_sample_time=0.05)
        return BenchResult("upstream", trace_name, backend, elements, times)
    if backend == "python-oracle":
        from ..oracle import OracleDocument

        def iter_fn():
            doc = OracleDocument.from_str(trace.start_content)
            for pos, d, ins in trace.iter_patches():
                doc.replace(pos, pos + d, ins)
            assert len(doc) == len(trace.end_content)

        times = measure(iter_fn, warmup=0, samples=max(2, samples // 2))
        return BenchResult("upstream", trace_name, backend, elements, times)
    if backend == "py-reconcile":
        from ..backends.reconcile import PyReconcile

        def iter_fn():
            doc = PyReconcile.from_str(trace.start_content)
            for pos, d, ins in trace.iter_patches():
                doc.replace(pos, pos + d, ins)
            assert len(doc) == len(trace.end_content.encode())

        times = measure(iter_fn, warmup=0, samples=max(2, samples // 2))
        return BenchResult("upstream", trace_name, backend, elements, times)
    if backend in ("jax", "jax-unit"):
        try:
            from ..backends.jax_backend import JaxReplayBackend
        except ImportError:
            return None

        b = JaxReplayBackend(
            n_replicas=replicas, batch=batch,
            layout="unit" if backend == "jax-unit" else None,
        )
        b.prepare(trace)
        times = measure(b.replay_once, warmup=warmup, samples=samples)
        if profile_dir:
            import jax

            with jax.profiler.trace(profile_dir):
                b.replay_once()
        return BenchResult(
            "upstream", trace_name, b.NAME, elements, times, replicas=replicas
        )
    if backend in ("jax-pos", "jax-range", "jax-runs", "jax-patch",
                   "jax-unitwire", "jax-flat"):
        return None  # downstream/merge-only variants
    raise ValueError(f"unknown backend {backend!r}")


def _native_upstreams() -> dict[str, type]:
    """Registered Upstream backends with a native whole-replay path
    (@register_upstream in backends/native.py populates the registry)."""
    try:
        from ..backends import native  # noqa: F401  (triggers registration)
    except OSError:
        pass
    return {
        name: cls
        for name, cls in upstream_backends().items()
        if hasattr(cls, "replay_patches")
    }


def run_downstream(trace_name: str, backend: str, samples: int,
                   warmup: int, replicas: int = 1,
                   batch: int = 256) -> BenchResult | None:
    trace = load_testing_data(trace_name)
    elements = len(trace)
    if backend == "cpp-crdt":
        from ..backends.native import CppCrdtDownstream, native_available

        if not native_available():
            return None
        down, _updates = CppCrdtDownstream.upstream_updates(trace)  # untimed
        end_len = len(trace.end_content)

        def iter_fn():
            n = down.apply_all_native()
            assert n == end_len

        times = measure(iter_fn, warmup=warmup, samples=samples,
                        min_sample_time=0.05)
        return BenchResult("downstream", trace_name, backend, elements, times)
    if backend in ("jax", "jax-pos", "jax-range", "jax-runs", "jax-patch",
                   "jax-unitwire"):
        try:
            from ..engine.downstream import JaxDownstreamBackend
            from ..engine.downstream_range import JaxRangeDownstreamBackend
            from ..engine.merge_range import JaxRunDownstreamBackend
        except ImportError:
            return None
        if backend == "jax-range":
            from ..backends.native import native_available

            if not native_available():
                return None  # range generation anchors on the native dump
            b = JaxRangeDownstreamBackend(n_replicas=replicas)
        elif backend == "jax-runs":
            b = JaxRunDownstreamBackend(n_replicas=replicas)
        elif backend == "jax-patch":
            b = JaxRunDownstreamBackend(
                n_replicas=replicas, granularity="patch"
            )
        elif backend == "jax-unitwire":
            b = JaxRunDownstreamBackend(
                n_replicas=replicas, granularity="unit"
            )
        else:
            b = JaxDownstreamBackend(
                n_replicas=replicas, batch=batch,
                engine="v3" if backend == "jax-pos" else None,
            )
        try:
            b.prepare(trace)
        except ValueError:
            return None  # capacity beyond this engine's bound: skip cell
        times = measure(b.replay_once, warmup=warmup, samples=samples)
        return BenchResult(
            "downstream", trace_name, b.NAME, elements, times,
            replicas=replicas,
        )
    return None


import functools


@functools.lru_cache(maxsize=4)
def _merge_sim(config: str, merge_ops: int, batch: int):
    """Build a MergeSimulation for a merge bench config (UNTIMED, like the
    reference's update generation):

    - ``traces``: two agents editing concurrently from an empty shared base
      — one replays rustcode, the other seph-blog1 (BASELINE.md config 4).
    - ``synthetic``: 16 agents of random interleaved edits totalling
      ~``merge_ops`` ops (config 5's adversarial-interleaving workload).
    """
    import numpy as np

    from ..engine.merge import MergeSimulation
    from ..traces.tensorize import tensorize

    if config == "traces":
        streams = [
            tensorize(load_testing_data("rustcode"), batch=batch),
            tensorize(load_testing_data("seph-blog1"), batch=batch),
        ]
        return MergeSimulation(streams, base="", batch=batch)
    if config in ("synthetic", "adversarial"):
        from ..traces.loader import TestData, TestTxn
        from ..traces.synth import random_patches

        n_agents = 16
        rng = np.random.default_rng(1234)
        base = "the quick brown fox jumps over the lazy dog " * 4
        # adversarial: merge_ops counts DELIVERED ops — the unique op set
        # is merge_ops/16, and the delivered stream is built by run_merge
        # as shuffled duplicated deliveries (capacity = unique inserts, so
        # the state fits VMEM kernels while the merge still chews through
        # the full delivered volume with dedup + idempotent integration).
        unique_ops = merge_ops // 16 if config == "adversarial" else merge_ops
        streams = []
        for _ in range(n_agents):
            patches, _ = random_patches(
                rng, unique_ops // n_agents, len(base)
            )
            streams.append(
                tensorize(
                    TestData(base, "", [TestTxn("", patches)]), batch=batch
                )
            )
        return MergeSimulation(streams, base=base, batch=batch)
    raise ValueError(f"unknown merge config {config!r}")


def _range_merge_sim(sim):
    """The ONE RunMergeSimulation schedule (batch/epoch) shared by the
    timed jax-range merge cell and its --verify check — a drift here
    would verify a different schedule than the one benchmarked.  The
    schedule is intentionally pinned (NOT the CLI --batch): W=512
    runs/batch measured ~1.5x over 256 on the traces config (fewer
    sequential batches; the W x W forest stays cheap).  Returns None
    when the workload exceeds the run engine's capacity bound — the
    caller skips the cell, matching run_downstream's convention."""
    from ..engine.merge_range import RunMergeSimulation

    try:
        return RunMergeSimulation(sim, batch=512, epoch=8)
    except ValueError:
        return None


def _delivered_log(sim, config: str, merge_ops: int):
    """The wire-delivered op stream for a merge cell: the plain union, or
    (adversarial) ~merge_ops shuffled ops where every unique op is
    delivered ~16 times — the duplicated/reordered-delivery fault model
    (CRDT idempotence at scale, BASELINE.md config 5)."""
    import numpy as np

    from ..engine.merge import OpLog

    if config != "adversarial":
        return sim.log
    reps = max(1, merge_ops // max(len(sim.log), 1))
    log = OpLog.concat([sim.log] * reps)
    rng = np.random.default_rng(99)
    perm = rng.permutation(len(log))
    return OpLog(
        *(getattr(log, f)[perm]
          for f in ("lamport", "agent", "kind", "elem", "origin", "ch"))
    )


def run_merge(config: str, backend: str, samples: int, warmup: int,
              replicas: int, batch: int, merge_ops: int,
              epoch: int = 32) -> BenchResult | None:
    """Concurrent-merge throughput: timed region = integrate the full
    (shuffle-independent) union of divergent op logs into a fresh replica
    AND confirm convergence (digest agreement across replicas).  Element =
    one op in the union.  The reference's merge capability is
    ``decode_and_add``/``doc.merge`` (src/rope.rs:222-235); it publishes no
    merge benchmark — these cells are the BASELINE.md config 4-5 targets."""
    import numpy as np

    sim = _merge_sim(config, merge_ops, batch)
    delivered = _delivered_log(sim, config, merge_ops)
    elements = len(delivered)
    if backend == "cpp-crdt":
        from ..backends.native import NativeMerge, native_available
        from ..engine.merge import to_native_ops

        if not native_available():
            return None
        ops = to_native_ops(sim, delivered)  # untimed translation
        base = "".join(
            chr(int(c)) for c in np.asarray(sim.chars)[: sim.n_base]
        )
        nm0 = NativeMerge(base)
        expect_len = nm0.integrate(*ops)
        del nm0

        def iter_fn():
            nm = NativeMerge(base)
            assert nm.integrate(*ops) == expect_len

        times = measure(iter_fn, warmup=warmup, samples=samples,
                        min_sample_time=0.05)
        return BenchResult("merge", config, backend, elements, times)
    if backend == "jax":
        import jax
        import jax.numpy as jnp

        from ..engine.downstream import down_packed_init
        from ..engine.merge import merge_oplogs_packed
        from ..utils.digest import doc_digest_packed

        # Mirror merge_packed's guards (this cell calls
        # merge_oplogs_packed directly): packed-fill overflow corrupts
        # content identically on every replica, so the in-region
        # convergence assert could NOT catch it.
        if sim.capacity >= 1 << 28:
            raise ValueError(
                f"merge/{config}: capacity {sim.capacity} >= 2^28 exceeds"
                " the packed fill range (int32 combo)"
            )
        # clamp epoch exactly as merge_packed does, so segments padding
        # matches the padded log length
        epoch = min(epoch, max(1, -(-max(len(delivered), 1) // sim.batch)))
        # Pad + upload the delivered log ONCE (the cpp baseline's
        # translation is likewise untimed); the timed region is
        # fresh-replica init + on-device sort/dedup/integrate +
        # convergence check.
        log = sim._padded(delivered, multiple=sim.batch * epoch)
        dev = [
            jnp.asarray(getattr(log, f))
            for f in ("lamport", "agent", "kind", "elem", "origin", "ch")
        ]
        # non-adversarial unions are concatenated per-agent sorted logs:
        # rank by count_le passes instead of the device sort
        segments = None
        if config != "adversarial":
            n = len(sim.log)
            n_pad = (-n) % (sim.batch * epoch)
            segments = tuple(
                len(l) for l in sim.agent_logs if len(l)
            ) + ((n_pad,) if n_pad else ())
            from ..engine.merge import MAX_AGENTS

            max_lamport = max(
                (int(l.lamport.max(initial=0)) for l in sim.agent_logs),
                default=0,
            )
            assert (
                max_lamport * MAX_AGENTS + MAX_AGENTS - 1
                < (1 << 31) - 1 - len(segments)
            ), "lamport too large for the packed rank key"
        digest_r = jax.jit(
            jax.vmap(doc_digest_packed, in_axes=(0, 0, None))
        )

        def iter_fn():
            state = merge_oplogs_packed(
                down_packed_init(replicas, sim.capacity, sim.n_base),
                *dev,
                batch=sim.batch,
                epoch=epoch,
                max_unique=len(sim.log),
                segments=segments,
            )
            d = digest_r(state.doc, state.length, sim.chars)
            converged = bool(
                np.asarray(jnp.all(jnp.min(d, 0) == jnp.max(d, 0)))
            )
            assert converged, "replicas diverged"

        times = measure(iter_fn, warmup=warmup, samples=samples)
        plat = jax.devices()[0].platform
        tag = f"-r{replicas}" if replicas > 1 else ""
        return BenchResult(
            "merge", config, f"jax-{plat}{tag}", elements, times,
            replicas=replicas,
        )
    if backend == "jax-range":
        import jax
        import jax.numpy as jnp

        from ..utils.digest import doc_digest_packed

        if config == "adversarial":
            return None  # duplicated-delivery fault injection stays unit-op
        rm = _range_merge_sim(sim)
        if rm is None or not rm.fast_ok:
            return None  # over capacity / precondition violated -> skip
        digest_r = jax.jit(
            jax.vmap(doc_digest_packed, in_axes=(0, 0, None))
        )

        def iter_fn():
            st = rm.merge(n_replicas=replicas)
            d = digest_r(st.doc, st.length, sim.chars)
            assert bool(
                np.asarray(jnp.all(jnp.min(d, 0) == jnp.max(d, 0)))
            ), "replicas diverged"

        times = measure(iter_fn, warmup=warmup, samples=samples)
        plat = jax.devices()[0].platform
        tag = f"-r{replicas}" if replicas > 1 else ""
        return BenchResult(
            "merge", config, f"jax-{plat}{tag}-range", elements, times,
            replicas=replicas,
        )
    if backend == "jax-flat":
        import jax
        import jax.numpy as jnp

        from ..engine.downstream_flat import make_flat_merge
        from ..utils.digest import doc_digest_packed

        # one-shot unit-granularity merge: exact for ANY delivered
        # stream (unit runs make the no-skip precondition vacuous),
        # including the adversarial duplicated/shuffled delivery the
        # run-granular cell must refuse.  make_flat_merge does the
        # untimed wire translation + guards; the timed region is its
        # returned callable (device dedup/sort/integrate) + digest.
        merge_once = make_flat_merge(sim, delivered, n_replicas=replicas)
        digest_r = jax.jit(
            jax.vmap(doc_digest_packed, in_axes=(0, 0, None))
        )

        def iter_fn():
            st = merge_once()
            d = digest_r(st.doc, st.length, sim.chars)
            assert bool(
                np.asarray(jnp.all(jnp.min(d, 0) == jnp.max(d, 0)))
            ), "replicas diverged"

        times = measure(iter_fn, warmup=warmup, samples=samples)
        plat = jax.devices()[0].platform
        tag = f"-r{replicas}" if replicas > 1 else ""
        return BenchResult(
            "merge", config, f"jax-{plat}{tag}-flat", elements, times,
            replicas=replicas,
        )
    return None


@functools.lru_cache(maxsize=8)
def _oracle_content(trace_name: str) -> str:
    """Oracle replay once per trace (it is a full per-op Python replay —
    shared across the (group x backend) verify cells)."""
    from ..oracle.text_oracle import replay_trace

    trace = load_testing_data(trace_name)
    want = replay_trace(trace)
    assert want == trace.end_content, "oracle self-check failed"
    return want


def verify_upstream(trace_name: str, backend: str, replicas: int,
                    batch: int) -> bool | None:
    """Byte-identity check for one upstream cell: decode the backend's
    final document and compare against the pure-Python oracle AND the
    trace's endContent (upgrading the reference's length-only assert,
    src/main.rs:35).  Returns None if the backend is unavailable."""
    trace = load_testing_data(trace_name)
    want = _oracle_content(trace_name)
    native_names = _native_upstreams()
    if backend in native_names:
        from ..backends.native import native_available

        if not native_available():
            return None
        cls = native_names[backend]
        if getattr(cls, "EDITS_USE_BYTE_OFFSETS", False):
            pa = patch_arrays(trace.chars_to_bytes(), bytes_mode=True)
        else:
            pa = patch_arrays(trace)
        if hasattr(cls, "replay_patches_content"):
            got = cls.replay_patches_content(pa)
        else:
            doc = cls.from_str(trace.start_content)
            t = (
                trace.chars_to_bytes()
                if getattr(cls, "EDITS_USE_BYTE_OFFSETS", False)
                else trace
            )
            for pos, d, ins in t.iter_patches():
                if d:
                    doc.remove(pos, pos + d)
                if ins:
                    doc.insert(pos, ins)
            got = doc.content()
            if got is None:
                # content-free backend (cpp-cola): the final length is its
                # ONLY observable — exactly what the reference's cola cell
                # asserts (src/main.rs:35) — so verify that, per-op AND
                # through the one-call replay path.
                return len(doc) == pa.end_len and (
                    cls.replay_patches(pa) == pa.end_len
                )
        return got == want
    if backend == "python-oracle":
        return True  # the oracle is the reference point
    if backend == "py-reconcile":
        from ..backends.reconcile import PyReconcile

        doc = PyReconcile.from_str(trace.start_content)
        for pos, d, ins in trace.iter_patches():
            doc.replace(pos, pos + d, ins)
        return doc.content() == want
    if backend in ("jax", "jax-unit"):
        try:
            from ..backends.jax_backend import JaxReplayBackend
        except ImportError:
            return None

        b = JaxReplayBackend(
            n_replicas=replicas, batch=batch,
            layout="unit" if backend == "jax-unit" else None,
        )
        b.prepare(trace)
        return b.final_content() == want
    return None


def verify_downstream(trace_name: str, backend: str, replicas: int,
                      batch: int) -> bool | None:
    trace = load_testing_data(trace_name)
    want = _oracle_content(trace_name)
    if backend == "cpp-crdt":
        from ..backends.native import CppCrdtDownstream, native_available

        if not native_available():
            return None
        down, _ = CppCrdtDownstream.upstream_updates(trace)
        down.apply_all_native()
        return down.content() == want
    if backend in ("jax", "jax-pos", "jax-range", "jax-runs", "jax-patch",
                   "jax-unitwire"):
        try:
            from ..engine.downstream import JaxDownstreamBackend
            from ..engine.downstream_range import JaxRangeDownstreamBackend
            from ..engine.merge_range import JaxRunDownstreamBackend
        except ImportError:
            return None
        if backend == "jax-range":
            from ..backends.native import native_available

            if not native_available():
                return None
            b = JaxRangeDownstreamBackend(n_replicas=replicas)
        elif backend == "jax-runs":
            b = JaxRunDownstreamBackend(n_replicas=replicas)
        elif backend == "jax-patch":
            b = JaxRunDownstreamBackend(
                n_replicas=replicas, granularity="patch"
            )
        elif backend == "jax-unitwire":
            b = JaxRunDownstreamBackend(
                n_replicas=replicas, granularity="unit"
            )
        else:
            b = JaxDownstreamBackend(
                n_replicas=replicas, batch=batch,
                engine="v3" if backend == "jax-pos" else None,
            )
        try:
            b.prepare(trace)
        except ValueError:
            return None  # capacity beyond this engine's bound: skip cell
        return b.final_content() == want
    return None


def verify_merge(config: str, merge_ops: int, batch: int,
                 replicas: int, epoch: int = 32,
                 engine: str = "unit") -> bool | None:
    """Byte-identity for a merge cell: the JAX merge's decoded document
    must equal the independent native treap's (engine/merge.py
    native_merge_content), at the same schedule the timed cell uses.
    ``engine``: 'unit' = packed unit-op merge; 'range' = run-granular
    merge (engine/merge_range.py); 'flat' = one-shot flatten
    (engine/downstream_flat.py)."""
    from ..backends.native import native_available
    from ..engine.merge import native_merge_content

    if not native_available():
        return None
    sim = _merge_sim(config, merge_ops, batch)
    delivered = _delivered_log(sim, config, merge_ops)
    if engine == "flat":
        from ..engine.downstream_flat import make_flat_merge

        st = make_flat_merge(sim, delivered, n_replicas=replicas)()
        want = native_merge_content(sim, delivered)
        return sim.decode(st) == want
    if engine == "range":
        if config == "adversarial":
            return None
        rm = _range_merge_sim(sim)
        if rm is None or not rm.fast_ok:
            return None
        want = native_merge_content(sim, delivered)
        return rm.decode(rm.merge(n_replicas=replicas)) == want
    want = native_merge_content(sim, delivered)
    if config == "adversarial":
        state = sim.merge_packed(
            log=delivered, n_replicas=replicas, epoch=epoch,
            max_unique=len(sim.log),
        )
    else:
        # same construction as the timed cell (sorted-segments rank path)
        state = sim.merge_packed(n_replicas=replicas, epoch=epoch)
    return sim.decode(state) == want


def run_serve(args) -> int:
    """The serve family: build/drain a document fleet (serve/bench.py),
    verify a per-class sample against the oracle, persist the artifact.
    Exits nonzero on a verification mismatch — and, in chaos mode
    (--serve-faults), when any injected fault goes unfired or
    unrecovered."""
    from ..serve.bench import (
        ensure_virtual_devices,
        run_serve_bench,
        run_serve_open_sweep,
        run_serve_soak,
    )

    if args.serve_edgecheck is not None:
        # the dtype-edge adversarial harness (serve/edgecheck.py) owns
        # its fleets, both kernels, and the armed range sanitizer —
        # flags that shape a bench drain are REJECTED, not silently
        # dropped (same contract as the replicated/open matrices below)
        unsupported = [
            ("--serve-writers", args.serve_writers > 1),
            ("--serve-open", args.serve_open is not None),
            ("--serve-soak", args.serve_soak is not None),
            ("--serve-longhaul", args.serve_longhaul > 0),
            ("--serve-recover", args.serve_recover),
            ("--serve-crash-round", args.serve_crash_round > 0),
            ("--serve-reshard", args.serve_reshard is not None),
            ("--serve-record-evict", args.serve_record_evict),
            ("--serve-mesh", args.serve_mesh > 1),
            ("--serve-tiers", args.serve_tiers is not None),
            ("--serve-stream", args.serve_stream),
            ("--serve-journal", args.serve_journal is not None),
            ("--serve-faults", args.serve_faults is not None),
        ]
        bad = [flag for flag, hit in unsupported if hit]
        if bad:
            print(
                f"{', '.join(bad)} not supported with "
                "--serve-edgecheck (the harness builds its own "
                "adversarial fleets and drains them through BOTH "
                "kernels, sanitizer armed)",
                file=sys.stderr,
            )
            return 2
        from ..serve.edgecheck import main as edge_main

        return edge_main(
            ["--small"] if args.serve_edgecheck == "small" else []
        )

    if args.serve_writers > 1:
        # replicated family: serve/repl/<mix>/<fleet>x<writers>
        # (serve/replicate/bench.py).  Exit gate is the verification
        # TIER: full-fleet byte convergence against the oracle AND the
        # RA-linearizability axioms over sampled broadcast histories —
        # plus the chaos gate when a fault plan is armed.
        from ..serve.replicate.bench import run_serve_repl_bench

        # unsupported combinations are REJECTED, not silently dropped —
        # a user who asked for a mesh or a bounded queue must not get a
        # run that quietly did neither (delivery pacing belongs to the
        # broadcast bus in replicated mode; mesh/telemetry/profiling of
        # the replicated family are future work, see ROADMAP)
        unsupported = [
            ("--serve-soak", args.serve_soak is not None),
            ("--serve-longhaul", args.serve_longhaul > 0),
            ("--serve-recover", args.serve_recover),
            ("--serve-crash-round", args.serve_crash_round > 0),
            ("--serve-reshard", args.serve_reshard is not None),
            ("--serve-record-evict", args.serve_record_evict),
            ("--serve-mesh", args.serve_mesh > 1),
            ("--serve-tiers", args.serve_tiers is not None),
            ("--serve-queue-cap", args.serve_queue_cap > 0),
            ("--serve-status", args.serve_status is not None),
            ("--serve-timeseries", args.serve_timeseries is not None),
            ("--serve-trace", args.serve_trace is not None),
            ("--serve-profile", args.serve_profile > 0),
            ("--serve-flight", args.serve_flight is not None),
            ("--serve-open", args.serve_open is not None),
            ("--serve-stream", args.serve_stream),
            ("--serve-stream-scaling",
             args.serve_stream_scaling is not None),
        ]
        bad = [flag for flag, hit in unsupported if hit]
        if bad:
            print(
                f"{', '.join(bad)} not supported with --serve-writers "
                "(the replicated family verifies the FULL fleet; "
                "delivery pacing is the broadcast bus's)",
                file=sys.stderr,
            )
            return 2
        r, info = run_serve_repl_bench(
            mix=args.serve_mix,
            n_docs=args.serve_docs,
            writers=args.serve_writers,
            batch=args.serve_batch,
            classes=args.serve_classes,
            slots=args.serve_slots,
            seed=args.serve_seed,
            arrival_span=args.serve_arrival_span,
            macro_k=args.serve_macro,
            batch_chars=args.serve_batch_chars,
            serve_kernel=args.serve_kernel,
            turn_ops=args.serve_turn_ops,
            journal_dir=args.serve_journal,
            snapshot_every=args.serve_snapshot_every,
            faults=args.serve_faults,
            save_name=args.serve_save_name,
            reqtrace_samples=args.serve_reqtrace,
            slo_spec=args.serve_slo,
            log=lambda m: print(m, file=sys.stderr),
        )
        rb = r.extra["replication"]
        conv = r.extra["convergence"]
        print(
            f"{r.bench_id}: {r.extra['patches_per_sec']:,.0f} "
            f"replica-patches/s, merge "
            f"{r.extra['merge_unit_ops_per_sec']:,.0f} unit-ops/s, "
            f"broadcast {rb['broadcast_bytes'] / 1024:.1f} KiB, "
            f"divergence max {rb['divergence_depth_max']} blocks, "
            f"converged {conv['replicas_checked']} replicas "
            f"(RA axioms {'ok' if conv['ra_ok'] else 'VIOLATED'})"
        )
        ok = info["verify_ok"] and info["ra_ok"] and info["faults_ok"]
        return 0 if ok else 1

    if args.serve_open is not None:
        # open-loop live serving: unsupported combinations are REJECTED,
        # not silently dropped (same contract as the replicated matrix
        # above) — recovery/longhaul replay a closed-loop journal tail,
        # the tiered family is its own bench id, and the ingest pump
        # feeds exactly one scheduler's bounded queues
        unsupported = [
            ("--serve-longhaul", args.serve_longhaul > 0),
            ("--serve-recover", args.serve_recover),
            ("--serve-crash-round", args.serve_crash_round > 0),
            ("--serve-reshard", args.serve_reshard is not None),
            ("--serve-mesh", args.serve_mesh > 1),
            ("--serve-tiers", args.serve_tiers is not None),
            ("--serve-stream", args.serve_stream),
        ]
        bad = [flag for flag, hit in unsupported if hit]
        if bad:
            print(
                f"{', '.join(bad)} not supported with --serve-open "
                "(the open-loop family serves live wire arrivals; "
                "see serve/ingest/)",
                file=sys.stderr,
            )
            return 2
        if args.serve_open_sweep is not None and args.serve_soak is not None:
            print(
                "--serve-open-sweep probes are one-shot drains; "
                "--serve-soak does not compose with the sweep",
                file=sys.stderr,
            )
            return 2
    else:
        # ingest-only flags without the front are configuration errors
        orphaned = [
            ("--serve-tenants", args.serve_tenants is not None),
            ("--serve-deadline", args.serve_deadline),
            ("--serve-deadline-budget", args.serve_deadline_budget > 0),
            ("--serve-open-sweep", args.serve_open_sweep is not None),
        ]
        bad = [flag for flag, hit in orphaned if hit]
        if bad:
            print(
                f"{', '.join(bad)} configure the live ingest front: "
                "--serve-open RATE is required",
                file=sys.stderr,
            )
            return 2

    if args.serve_record_evict and args.serve_journal is not None:
        print(
            "--serve-record-evict requires a journal-less drain: "
            "recovery re-adopts the spool members the GC reclaims",
            file=sys.stderr,
        )
        return 2

    if args.serve_stream_scaling is not None and (
            args.serve_soak is not None
            or args.serve_open_sweep is not None):
        print(
            "--serve-stream-scaling attaches the fleet-size probe "
            "table to ONE serve run's artifact; it does not compose "
            "with --serve-soak / --serve-open-sweep",
            file=sys.stderr,
        )
        return 2

    mesh_devices = ensure_virtual_devices(args.serve_mesh)
    common = dict(
        mix=args.serve_mix,
        n_docs=args.serve_docs,
        batch=args.serve_batch,
        classes=args.serve_classes,
        slots=args.serve_slots,
        arrival_span=args.serve_arrival_span,
        arrival_dist=args.serve_arrival_dist,
        mesh_devices=mesh_devices,
        verify_sample=args.serve_verify_sample,
        stream=args.serve_stream,
        sample_seed=args.serve_sample_seed,
        macro_k=args.serve_macro,
        batch_chars=args.serve_batch_chars,
        serve_kernel=args.serve_kernel,
        serve_tiers=args.serve_tiers,
        journal_dir=args.serve_journal,
        snapshot_every=args.serve_snapshot_every,
        snapshot_keep=args.serve_snapshot_keep,
        snapshot_full_every=args.serve_full_every,
        wal_segment_bytes=args.serve_wal_segment_bytes,
        longhaul=args.serve_longhaul,
        measure_recovery=args.serve_recover,
        crash_after=args.serve_crash_round,
        reshard_spec=args.serve_reshard,
        record_evict=args.serve_record_evict,
        faults=args.serve_faults,
        queue_cap=args.serve_queue_cap,
        overflow_policy=args.serve_overflow_policy,
        open_spec=args.serve_open,
        tenants_spec=args.serve_tenants,
        deadline=args.serve_deadline,
        deadline_budget=args.serve_deadline_budget,
        save_name=args.serve_save_name,
        trace_path=args.serve_trace,
        profile_rounds=args.serve_profile,
        reqtrace_samples=args.serve_reqtrace,
        slo_spec=args.serve_slo,
        flight_path=args.serve_flight,
        log=lambda m: print(m, file=sys.stderr),
    )
    if args.serve_soak is not None:
        # soak mode: repeated drains under one continuous telemetry
        # bundle with the anomaly detectors armed; an anomaly still
        # active at soak end fails the run (exit nonzero below)
        r, info = run_serve_soak(
            soak_seconds=args.serve_soak,
            seed=args.serve_seed,
            status_port=args.serve_status,
            timeseries_path=args.serve_timeseries,
            timeseries_window=args.serve_timeseries_window,
            watchdog_s=args.serve_watchdog,
            **common,
        )
    elif args.serve_open_sweep is not None:
        # knee sweep: probe each offered rate, then run the configured
        # rate as the final artifact-bearing drain (knee block attached)
        try:
            rates = [float(x) for x in
                     args.serve_open_sweep.split(",") if x.strip()]
        except ValueError:
            print(
                f"--serve-open-sweep: bad rate list "
                f"{args.serve_open_sweep!r}",
                file=sys.stderr,
            )
            return 2
        sweep_kw = dict(common)
        sweep_kw.pop("open_spec")
        sweep_kw.pop("save_name")
        r, info = run_serve_open_sweep(
            rates,
            open_spec=args.serve_open,
            save_name=args.serve_save_name,
            seed=args.serve_seed,
            status_port=args.serve_status,
            timeseries_path=args.serve_timeseries,
            timeseries_window=args.serve_timeseries_window,
            **sweep_kw,
        )
    else:
        scaling_rows = None
        if args.serve_stream_scaling:
            # fleet-size scaling probe: one fresh subprocess per
            # (size, mode) cell — ru_maxrss is process-monotonic, so
            # per-cell peaks need per-cell processes.  The table rides
            # the main run's artifact (construction.scaling).
            from ..serve.construction import scaling_table

            try:
                sizes = [int(x) for x in
                         args.serve_stream_scaling.split(",")
                         if x.strip()]
            except ValueError:
                print(
                    f"--serve-stream-scaling: bad size list "
                    f"{args.serve_stream_scaling!r}",
                    file=sys.stderr,
                )
                return 2
            scaling_rows = scaling_table(
                sizes,
                mix=args.serve_mix,
                seed=args.serve_seed,
                arrival_span=args.serve_arrival_span,
                arrival_dist=args.serve_arrival_dist,
                serve_tiers=args.serve_tiers,
                log=lambda m: print(m, file=sys.stderr),
            )
        r, info = run_serve_bench(
            seed=args.serve_seed,
            status_port=args.serve_status,
            timeseries_path=args.serve_timeseries,
            timeseries_window=args.serve_timeseries_window,
            construction_scaling=scaling_rows,
            **common,
        )
    print(
        f"{r.bench_id}: {r.elements_per_sec:,.0f} patches/s "
        f"(K={r.extra['macro_k']}, kernel={r.extra['kernel']}, "
        "steady batch latency "
        f"p50 {r.extra['batch_latency']['p50'] * 1e3:.1f}ms "
        f"/ p99 {r.extra['batch_latency']['p99'] * 1e3:.1f}ms, "
        f"compile {r.extra['compile_time']:.2f}s, "
        f"coalesce x{r.extra['coalesce_ratio']:.2f}, "
        f"pad {r.extra['pad_fraction']:.3f})"
    )
    if r.extra.get("residency") is not None:
        res = r.extra["residency"]
        hr = res.get("hit_rate")
        print(
            f"  residency: hot {res['hot_rows_budget']} rows / warm "
            f"{res['warm_budget']} docs / cold compressed; warm hits "
            f"{res['warm_hits']} (prefetched {res['prefetch_hits']}), "
            f"cold restores {res['cold_restores']}, hit rate "
            + (f"{hr:.3f}" if hr is not None else "n/a")
        )
    if r.extra.get("ingest") is not None:
        ing = r.extra["ingest"]
        fr = ing["front"]
        dl = ing["deadline"]
        tenants = ing["admission"]["tenants"]
        hit = dl.get("hit_rate")
        print(
            f"  ingest: {fr['ops_delivered']} ops / "
            f"{fr['ops_frames']} frames over {fr['sessions_opened']} "
            f"sessions ({fr['sessions_resumed']} resumed, "
            f"{fr['churn_drops']} churn drops); "
            + "; ".join(
                f"{t}: admit {d['admitted_ops']} defer "
                f"{d['deferred_ops']} shed {d['shed_ops']}"
                for t, d in sorted(tenants.items())
            )
            + (f"; deadline hit rate {hit:.3f} "
               f"({'EDF' if dl['edf'] else 'rr'})"
               if hit is not None else "")
        )
    if r.extra.get("knee") is not None:
        knee = r.extra["knee"]
        print(
            f"  knee: capacity {knee['capacity_ops_per_round']:.1f} "
            f"ops/round over {len(knee['points'])} probes — "
            + ", ".join(
                f"u={p['utilization']:.2f}:p99 {p['p99_ms']:.1f}ms"
                for p in knee["points"]
            )
        )
    if r.extra["faults"] is not None:
        f = r.extra["faults"]
        mttr = r.extra["mttr_rounds"]
        print(
            f"  chaos: {f['injected']} injected / {f['recovered']} "
            f"recovered ({f['not_fired']} not fired), "
            f"MTTR {mttr['mean']:.1f} rounds (max {mttr['max']}), "
            f"replayed {r.extra['ops_replayed']} ops, "
            f"shed {r.extra['shed_ops']}, "
            f"quarantines {len(r.extra['quarantines'])}, "
            f"degraded rounds {r.extra['degraded_rounds']}"
        )
    if r.extra.get("recovery") is not None:
        rec = r.extra["recovery"]
        print(
            f"  recovery: {rec['recover_ms']:.1f}ms restore "
            f"(snapshot round {rec['snapshot_round']}, chain depth "
            f"{rec['chain_depth']}, {rec['chain_fallbacks']} fallbacks)"
            f" + {rec['redo_ms']:.1f}ms redo of {rec['redo_ops']} ops, "
            f"WAL {rec['journal_disk_bytes']} B on disk, "
            f"verify {'ok' if rec['verify_ok'] else 'FAILED'}"
        )
    if r.extra.get("reshard") is not None:
        rs = r.extra["reshard"]
        mid = rs["mid_latency"]
        print(
            f"  reshard: {rs['kind']} {rs['shards']} {rs['state']} "
            f"(rounds {rs['begin_round']}..{rs['commit_round']}); "
            f"{rs['migrated']} row moves + {rs['evicted']} demotions, "
            f"{rs['deferred_lanes']} lanes / {rs['deferred_ops']} ops "
            f"deferred, {rs['resumes']} crash resumes"
            + (f"; mid-reshard round p99 {mid['p99'] * 1e3:.1f}ms"
               if mid else "")
        )
    if r.extra.get("anomalies") is not None:
        a = r.extra["anomalies"]
        print(
            f"  soak: {info.get('iterations', 1)} drain(s), "
            f"anomalies {a['fired']} fired / {a['uncleared']} uncleared"
            + (f" (active: {', '.join(a['active'])})" if a["active"]
               else "")
        )
    ok = (
        info["verify_ok"] and info["faults_ok"]
        and info.get("anomalies_ok", True)
    )
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--family", default="classic", choices=("classic", "serve"),
        help="'classic' = the per-trace replay matrix; 'serve' = the "
             "multi-tenant document-fleet engine (serve/)",
    )
    ap.add_argument("--serve-docs", type=int, default=4096)
    ap.add_argument("--serve-mix", default="mixed",
                    help="workload mix name (serve/workload.py MIXES)")
    ap.add_argument("--serve-batch", type=int, default=64,
                    help="coalesced range ops per doc per device round")
    ap.add_argument("--serve-macro", type=int, default=8, metavar="K",
                    help="macro-round depth: K staged rounds per device "
                         "dispatch (lax.scan; 1 = legacy per-round "
                         "dispatch through the same machinery)")
    ap.add_argument("--serve-batch-chars", type=int, default=256,
                    help="inserted chars per doc per device round (bounds "
                         "the expansion nbits; insert runs are pre-split "
                         "to fit)")
    ap.add_argument("--serve-kernel", default="fused",
                    choices=("fused", "scan"),
                    help="serve-step kernel: 'fused' = the "
                         "ops/serve_fused.py path (shared resolve "
                         "executables + packed narrow op lanes; one "
                         "VMEM-resident pallas_call per macro dispatch "
                         "on TPU), 'scan' = the legacy per-shape "
                         "resolve+apply lax.scan body (the differential "
                         "baseline).  Recorded in the artifact as "
                         "extra['kernel']")
    ap.add_argument("--serve-edgecheck", default=None,
                    choices=("small", "full"), metavar="MODE",
                    help="run the dtype-edge adversarial harness "
                         "(serve/edgecheck.py) instead of a bench "
                         "drain: adversarial fleets through BOTH "
                         "kernels with the range sanitizer armed, "
                         "oracle byte-verified, plus the seeded "
                         "boundary-contract fuzz.  'small' keeps the "
                         "structural edges; 'full' adds the two "
                         "uint16-bracket ladders.  Exit 0 clean / 1 "
                         "violation / 2 usage")
    ap.add_argument("--serve-save-name", default=None,
                    help="artifact basename (default serve_<mix>_<docs>)")
    ap.add_argument("--serve-journal", default=None, metavar="DIR",
                    help="enable the write-ahead op journal + snapshot "
                         "barriers in DIR ('auto' = an owned temp dir, "
                         "removed after the run)")
    ap.add_argument("--serve-snapshot-every", type=int, default=32,
                    metavar="N",
                    help="fleet snapshot barrier period in macro-rounds "
                         "(journal mode only)")
    ap.add_argument("--serve-snapshot-keep", type=int, default=2,
                    metavar="N",
                    help="retained snapshot CHAINS (a delta's base "
                         "links always survive with it; <=0 = never "
                         "prune).  Also the WAL GC floor: segments "
                         "are kept back to the oldest retained "
                         "barrier so chain fallback always finds its "
                         "redo tail")
    ap.add_argument("--serve-full-every", type=int, default=4,
                    metavar="N",
                    help="every Nth barrier is a chain-rooting FULL "
                         "snapshot; the barriers between persist only "
                         "rows dirty since the previous one as a "
                         "CRC-chained DELTA (1 = every barrier full, "
                         "the pre-delta behavior)")
    ap.add_argument("--serve-wal-segment-bytes", type=int,
                    default=1 << 20, metavar="BYTES",
                    help="roll the active WAL file into a sealed "
                         "numbered segment past this size; segments "
                         "fully covered by a committed snapshot are "
                         "garbage-collected crash-safely (0 = never "
                         "roll, the pre-segmentation behavior)")
    ap.add_argument("--serve-longhaul", type=int, default=0,
                    metavar="H",
                    help="the serve/longhaul/<mix>/<fleet> durability "
                         "family: synthetic streams carry H-times the "
                         "band op count (days-of-edits scale), the "
                         "journal is required, and the run ends with a "
                         "measured recovery leg (recover_ms + redo "
                         "span + chain depth in the artifact, gated "
                         "by tools/bench_compare.py)")
    ap.add_argument("--serve-recover", action="store_true",
                    help="measure the recovery-time objective after "
                         "the drain: drop the live fleet, recover a "
                         "fresh one from the journal directory, "
                         "resume the redo tail, byte-verify vs the "
                         "oracle (requires --serve-journal)")
    ap.add_argument("--serve-crash-round", type=int, default=0,
                    metavar="N",
                    help="inject a crash: kill the drain after N "
                         "macro-rounds and gate the run on the "
                         "recovered fleet's oracle byte-verify "
                         "(implies --serve-recover)")
    ap.add_argument("--serve-faults", default=None, metavar="SPEC",
                    help="seeded chaos drain: serve/faults.py spec, e.g. "
                         "'seed=7,span=8,spool_corrupt=1,device_loss=1,"
                         "queue_overflow=1,dup_batch=1,stall=1'")
    ap.add_argument("--serve-reshard", default=None, metavar="SPEC",
                    help="live shard-map change mid-drain "
                         "(serve/reshard.py): 'shrink:FROM:TO[@R]', "
                         "'grow:FROM:TO[@R]' or 'drain:S[,of=N]'; "
                         "options batch=N (doc moves per round), "
                         "imbalance=X (PR 7 gauge trigger).  Requires "
                         "--serve-journal; its own bench family "
                         "serve/reshard/<mix>/<fleet>")
    ap.add_argument("--serve-record-evict", action="store_true",
                    help="reclaim drained docs' pool records + spool "
                         "members mid-drain (two-phase GC, "
                         "serve/pool.py gc_drained_docs): steady-state "
                         "footprint tracks the ACTIVE set, not the "
                         "fleet.  Journal-less drains only (recovery "
                         "re-adopts spool members)")
    ap.add_argument("--serve-queue-cap", type=int, default=0,
                    help="bound each doc's pending op queue (0 = "
                         "unbounded legacy behavior; overflow past the "
                         "cap is an explicit defer/shed decision)")
    ap.add_argument("--serve-overflow-policy", default="defer",
                    choices=("defer", "shed"),
                    help="decision at a queue-cap overflow: backpressure "
                         "the producer (defer) or tail-drop the "
                         "session's remaining ops (shed; surfaced as "
                         "shed_ops + lossy_docs)")
    ap.add_argument("--serve-classes", default="256,1024,4096,8192,49152",
                    help="capacity classes (slots per doc, ascending; the "
                         "largest must hold the biggest workload doc — "
                         "'mixed' hosts rustcode windows at ~43.7k slots)")
    ap.add_argument("--serve-slots", default="2048,512,128,32,16",
                    help="resident rows per capacity class")
    ap.add_argument("--serve-mesh", type=int, default=0,
                    help="shard docs over N (virtual CPU) mesh devices")
    ap.add_argument("--serve-tiers", default=None, metavar="SPEC",
                    help="tiered state residency, 'hot=ROWS,warm=DOCS': "
                         "scale the per-class device-row budget to "
                         "~ROWS total (>= 2 rows per class; omit hot= "
                         "to keep --serve-slots) and bound the pinned-"
                         "host warm tier at DOCS ready-to-upload rows "
                         "(arms the serve/prefetch.py async "
                         "prefetcher; cold spool writes become "
                         "compressed).  Bench ids become "
                         "serve/tier/<mix>/<fleet>")
    ap.add_argument("--serve-arrival-dist", default="uniform",
                    choices=("uniform", "zipf"),
                    help="session arrival staggering over "
                         "--serve-arrival-span: 'uniform' (legacy) or "
                         "'zipf' — a dense early head plus a long "
                         "trickling tail, the skew that makes the "
                         "warm tier's hot set real")
    ap.add_argument("--serve-stream", action="store_true",
                    help="streaming fleet construction: the fleet is a "
                         "lazy FleetSpec (per-doc band/arrival/trace "
                         "seed derived from (seed, doc_id), byte-"
                         "stable vs the eager build) and docs are born "
                         "in the pool's genesis state — traces "
                         "tensorize on first admission (off-drain via "
                         "the prefetch thread under --serve-tiers), so "
                         "setup cost/RSS scale with the active set, "
                         "not the fleet.  The artifact's "
                         "'construction' block carries "
                         "construction_ms + RSS either way")
    ap.add_argument("--serve-sample-seed", type=int, default=None,
                    metavar="SEED",
                    help="seed for the sampled oracle verify draw "
                         "(default: --serve-seed + 1); recorded in the "
                         "artifact (construction.verify_sample_seed) "
                         "next to the picked doc ids, so any sample "
                         "is re-drawable and auditable offline")
    ap.add_argument("--serve-stream-scaling", default=None,
                    metavar="N1,N2,...",
                    help="before the main run, probe construction "
                         "cost (no drain) at each fleet size in a "
                         "FRESH subprocess per (size, mode) cell — "
                         "stream rows at every size, eager contrast "
                         "rows up to 65536 docs — and attach the "
                         "fleet-size-vs-construction_ms/RSS table to "
                         "the artifact (construction.scaling)")
    ap.add_argument("--serve-trace", default=None, metavar="PATH",
                    help="arm the obs/trace.py span tracer for the "
                         "drain and write Perfetto-loadable Chrome "
                         "trace JSON to PATH (CRDT_BENCH_TRACE=1 arms "
                         "it too, defaulting next to the artifact)")
    ap.add_argument("--serve-profile", type=int, default=0, metavar="N",
                    help="capture a jax.profiler device trace of N "
                         "steady (non-compile, non-barrier) macro-"
                         "rounds; a top-ops table lands in the "
                         "artifact's profile block")
    ap.add_argument("--serve-status", type=int, default=None,
                    metavar="PORT",
                    help="start the obs/status.py live status server "
                         "on PORT (0 = ephemeral, bound port logged): "
                         "/healthz, /status.json, and /metrics in "
                         "Prometheus text exposition")
    ap.add_argument("--serve-timeseries", default=None, metavar="PATH",
                    help="stream closed obs/timeseries.py windows as "
                         "JSONL to PATH (also arms the windowed "
                         "recorder: the artifact gains a versioned "
                         "'timeseries' block + per-shard series)")
    ap.add_argument("--serve-timeseries-window", type=int, default=8,
                    metavar="N",
                    help="macro-rounds folded per time-series window")
    ap.add_argument("--serve-reqtrace", type=int, default=0,
                    metavar="N",
                    help="arm obs/reqtrace.py request-scoped causal "
                         "tracing, keeping the last N sampled request "
                         "traces (0 = disarmed; the artifact gains a "
                         "versioned 'reqtrace' block with per-request "
                         "segment breakdowns, publish-point hops and "
                         "histogram exemplars)")
    ap.add_argument("--serve-slo", default=None, metavar="SPEC",
                    help="per-class latency objectives, "
                         "class=pQ:MS[,class=pQ:MS...] — e.g. "
                         "'default=p99:250,c4096=p99.9:1500'; arms "
                         "request tracing, exports rolling burn-rate "
                         "gauges on /metrics + /status.json, and adds "
                         "a versioned 'slo' artifact block gated by "
                         "tools/bench_compare.py")
    ap.add_argument("--serve-flight", default=None, metavar="PATH",
                    help="arm the obs/flight.py anomaly flight "
                         "recorder: a bounded ring of recent rounds + "
                         "sampled request traces + registry snapshot, "
                         "dumped atomically to PATH on anomaly fire, "
                         "unrecovered fault, or crash (validate with "
                         "python -m crdt_benches_tpu.obs.flight PATH)")
    ap.add_argument("--serve-soak", type=float, default=None,
                    metavar="SECONDS",
                    help="soak mode: drain re-seeded fleets back-to-"
                         "back for SECONDS (0 = one drain) under one "
                         "continuous telemetry bundle with the obs/"
                         "anomaly.py detectors armed (throughput "
                         "degradation, RSS/journal leak, stuck-round "
                         "watchdog); exits nonzero when an anomaly is "
                         "still active at soak end")
    ap.add_argument("--serve-watchdog", type=float, default=0.0,
                    metavar="SECONDS",
                    help="stuck-round watchdog threshold for soak "
                         "mode (0 = auto: 25x the rolling median "
                         "steady-round latency, floored at 1s)")
    ap.add_argument("--serve-writers", type=int, default=0, metavar="W",
                    help="replicate every served doc across W writer "
                         "replicas (serve/replicate/): bench ids "
                         "become serve/repl/<mix>/<fleet>x<W>, the run "
                         "gates on full-fleet convergence + the "
                         "RA-linearizability checker (0/1 = the plain "
                         "single-writer family)")
    ap.add_argument("--serve-turn-ops", type=int, default=64,
                    metavar="N",
                    help="coalesced ops per writer turn block (the "
                         "replication authorship/broadcast unit)")
    ap.add_argument("--serve-open", default=None, metavar="RATE",
                    help="open-loop live serving (serve/ingest/): start "
                         "the sessioned TCP ingest front and offer "
                         "RATE ops/macro-round over seeded arrivals — "
                         "'RATE' or 'RATE:poisson' / 'RATE:burst'.  "
                         "Bench ids become serve/open/<mix>/<fleet>; "
                         "the per-doc queue cap defaults on (8*batch) "
                         "and delivery flows exclusively through "
                         "per-tenant admission control")
    ap.add_argument("--serve-tenants", default=None, metavar="SPEC",
                    help="ingest admission tenants, "
                         "'name=RATE[:BURST[:BUDGET]],...' — token "
                         "refill per round, bucket depth (default "
                         "4*RATE), in-queue op budget (default "
                         "unbounded); e.g. 'gold=256:1024,"
                         "free=16:32:256' (requires --serve-open)")
    ap.add_argument("--serve-deadline", action="store_true",
                    help="earliest-deadline-first selection over "
                         "per-class latency budgets (serve/ingest/"
                         "deadline.py) instead of round-robin "
                         "(requires --serve-open)")
    ap.add_argument("--serve-deadline-budget", type=int, default=0,
                    metavar="N",
                    help="default per-doc deadline budget in macro-"
                         "rounds past arrival (0 = auto from the "
                         "offered load)")
    ap.add_argument("--serve-open-sweep", default=None, metavar="RATES",
                    help="offered-load sweep: probe the open-loop "
                         "drain at each comma-separated rate, then "
                         "run --serve-open's configured rate as the "
                         "artifact-bearing final run with the "
                         "p99-vs-utilization knee curve attached "
                         "(requires --serve-open)")
    ap.add_argument("--serve-seed", type=int, default=0)
    ap.add_argument("--serve-arrival-span", type=int, default=8)
    ap.add_argument("--serve-verify-sample", type=int, default=8,
                    help="docs byte-verified vs the oracle, spread "
                         "across every capacity class")
    ap.add_argument("--traces", default=",".join(TRACES))
    ap.add_argument("--backends", default="cpp-rope,cpp-crdt,cpp-cola,jax")
    ap.add_argument("--filter", default="", help="substring filter on group")
    ap.add_argument(
        "--only", default="",
        help="substring filter on the FULL bench id 'group/trace/backend' "
             "(e.g. 'downstream/rustcode/jax-patch' or just "
             "'automerge-paper/jax') — the whole-id filtering Criterion's "
             "CLI offers via BenchmarkId (reference src/main.rs:27)",
    )
    ap.add_argument("--samples", type=int, default=5)
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument(
        "--merge-configs", default="traces,synthetic",
        help="merge-group workloads (run with --filter merge): 'traces' = "
             "rustcode+seph-blog1 concurrent agents, 'synthetic' = 16-agent "
             "random interleaving of ~--merge-ops ops",
    )
    ap.add_argument("--merge-ops", type=int, default=1_000_000)
    ap.add_argument("--epoch", type=int, default=32,
                    help="id->position snapshot rebuild period (batches)")
    ap.add_argument("--save-baseline", default=None)
    ap.add_argument("--baseline", default=None)
    ap.add_argument(
        "--profile", default=None, metavar="DIR",
        help="capture a jax.profiler trace of one jax-backend iteration "
             "into DIR (the tracing capability Criterion leaves to external "
             "tools; view with TensorBoard/XProf)",
    )
    ap.add_argument(
        "--verify", action="store_true",
        help="byte-compare every (group x trace x backend) cell's final "
             "document against the pure-Python oracle (upgrades the "
             "reference's length-only assert, src/main.rs:35,68); exits "
             "nonzero on any mismatch",
    )
    ap.add_argument(
        "--verify-only", action="store_true",
        help="run --verify checks without timing anything",
    )
    args = ap.parse_args(argv)

    if args.family == "serve":
        return run_serve(args)

    if args.verify or args.verify_only:
        failures = []
        for trace in args.traces.split(","):
            for backend in args.backends.split(","):
                for group, fn in (
                    ("upstream", verify_upstream),
                    ("downstream", verify_downstream),
                ):
                    if args.filter and args.filter not in group:
                        continue
                    ok = fn(trace, backend, args.replicas, args.batch)
                    if ok is None:
                        continue
                    tag = "ok" if ok else "MISMATCH"
                    print(
                        f"verify {group}/{trace}/{backend}: {tag}",
                        file=sys.stderr,
                    )
                    if not ok:
                        failures.append((group, trace, backend))
        if not args.filter or args.filter in "merge":
            for config in args.merge_configs.split(","):
                for engine in ("unit", "range", "flat"):
                    ok = verify_merge(
                        config, args.merge_ops, args.batch, args.replicas,
                        args.epoch, engine=engine,
                    )
                    if ok is None:
                        continue
                    tag = "ok" if ok else "MISMATCH"
                    print(
                        f"verify merge/{config}/jax-{engine}: {tag}",
                        file=sys.stderr,
                    )
                    if not ok:
                        failures.append(("merge", config, f"jax-{engine}"))
        if failures:
            print(f"verify FAILED: {failures}", file=sys.stderr)
            return 1
        if args.verify_only:
            print("verify: all cells byte-identical", file=sys.stderr)
            return 0

    def _report(r: BenchResult) -> None:
        """Per-cell line with median AND min/max plus outlier annotation
        (criterion-style visibility, VERDICT r3 missing #1)."""
        o = r.outliers
        note = ""
        if o["mild"] or o["severe"]:
            note = f"  [outliers: {o['mild']} mild, {o['severe']} severe]"
        disc = getattr(r.samples, "discarded", [])
        if disc:
            note += (
                f"  [re-ran {len(disc)} severe: "
                + ", ".join(f"{x:.3g}s" for x in disc) + "]"
            )
        print(
            f"{r.bench_id}: median {r.median * 1e3:.2f}ms "
            f"(min {r.best * 1e3:.2f} / max {r.worst * 1e3:.2f}) -> "
            f"{r.elements_per_sec:,.0f} el/s{note}",
            file=sys.stderr,
        )

    def want(group: str, trace: str, backend: str) -> bool:
        return (
            not args.only or args.only in f"{group}/{trace}/{backend}"
        )

    results: list[BenchResult] = []
    for trace in args.traces.split(","):
        for backend in args.backends.split(","):
            if (not args.filter or args.filter in "upstream") and want(
                "upstream", trace, backend
            ):
                r = run_upstream(trace, backend, args.samples, args.warmup,
                                 args.replicas, args.batch,
                                 profile_dir=args.profile)
                if r:
                    results.append(r)
                    _report(r)
            if backend in (
                "cpp-crdt", "jax", "jax-pos", "jax-range", "jax-runs",
                "jax-patch", "jax-unitwire",
            ) and (not args.filter or args.filter in "downstream") and want(
                "downstream", trace, backend
            ):
                r = run_downstream(trace, backend, args.samples, args.warmup,
                                   replicas=args.replicas, batch=args.batch)
                if r:
                    results.append(r)
                    _report(r)

    if (args.filter and args.filter in "merge") or (
        args.only and args.only.startswith("merge")
    ):
        # an --only merge/... selection must reach the merge loop even
        # without --filter merge (code-review r5)
        for config in args.merge_configs.split(","):
            for backend in args.backends.split(","):
                if not want("merge", config, backend):
                    continue
                r = run_merge(config, backend, args.samples, args.warmup,
                              args.replicas, args.batch, args.merge_ops,
                              epoch=args.epoch)
                if r:
                    results.append(r)
                    _report(r)

    print(markdown_table(results))
    save_results(results, "latest")
    if args.save_baseline:
        save_results(results, args.save_baseline)
    if args.baseline:
        print("\n".join(compare_to_baseline(results, args.baseline)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
