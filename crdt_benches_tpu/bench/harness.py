"""Criterion-equivalent measurement harness.

Re-provides the measurement capabilities the reference gets from the
``criterion`` crate (reference src/main.rs:25-37 and SURVEY.md section 2.2):
warmup, repeated timed samples, robust statistics (median/mean/stddev/min),
throughput in **elements/sec where element = one trace patch**
(``Throughput::Elements``, reference src/main.rs:25), benchmark ids of the
form ``group/trace/backend`` (``BenchmarkId::new``, src/main.rs:27), JSON
result persistence, and named baseline save/compare (criterion's
``--save-baseline`` / ``--baseline`` CLI capability).
"""

from __future__ import annotations

import json
import math
import os
import time
from dataclasses import asdict, dataclass, field
from typing import Callable

RESULTS_DIR = "bench_results"


@dataclass
class Sample:
    seconds: float


@dataclass
class BenchResult:
    group: str  # "upstream" | "downstream" | ...
    trace: str
    backend: str
    elements: int  # throughput element count (= patch count)
    samples: list[float] = field(default_factory=list)
    replicas: int = 1
    extra: dict = field(default_factory=dict)

    @property
    def bench_id(self) -> str:
        return f"{self.group}/{self.trace}/{self.backend}"

    @property
    def median(self) -> float:
        s = sorted(self.samples)
        n = len(s)
        return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])

    @property
    def mean(self) -> float:
        return sum(self.samples) / len(self.samples)

    @property
    def stddev(self) -> float:
        if len(self.samples) < 2:
            return 0.0
        m = self.mean
        return math.sqrt(sum((x - m) ** 2 for x in self.samples) / (len(self.samples) - 1))

    @property
    def best(self) -> float:
        return min(self.samples)

    @property
    def worst(self) -> float:
        return max(self.samples)

    @property
    def p50(self) -> float:
        return quantiles(self.samples)["p50"]

    @property
    def p95(self) -> float:
        return quantiles(self.samples)["p95"]

    @property
    def p99(self) -> float:
        return quantiles(self.samples)["p99"]

    @property
    def outliers(self) -> dict:
        """Tukey classification of this cell's final kept samples."""
        return classify_outliers(self.samples)

    @property
    def elements_per_sec(self) -> float:
        """Criterion throughput: elements / median sample time, scaled by the
        replica count for batched backends (aggregate throughput)."""
        return self.elements * self.replicas / self.median

    def to_dict(self) -> dict:
        d = asdict(self)
        d.update(
            median=self.median,
            mean=self.mean,
            stddev=self.stddev,
            min=self.best,
            max=self.worst,
            **quantiles(self.samples),
            elements_per_sec=self.elements_per_sec,
            outliers=self.outliers,
        )
        # measure() hands back a SampleList carrying any samples it
        # discarded as severe outliers and re-ran — persist them so every
        # committed artifact is self-describing (VERDICT r3 missing #1).
        discarded = getattr(self.samples, "discarded", [])
        if discarded:
            d["discarded_outliers"] = list(discarded)
        return d


def _quantile(sorted_s: list[float], p: float) -> float:
    n = len(sorted_s)
    k = p * (n - 1)
    f = math.floor(k)
    c = min(f + 1, n - 1)
    return sorted_s[f] + (sorted_s[c] - sorted_s[f]) * (k - f)


def quantiles(samples, ps=(0.5, 0.95, 0.99)) -> dict[str, float]:
    """Linear-interpolated quantiles as a {"p50": ..., "p95": ..., ...}
    table (the serve family's per-batch latency report; same
    interpolation as the Tukey fences above)."""
    if not samples:
        raise ValueError("quantiles of an empty sample list")
    s = sorted(samples)
    return {f"p{100 * p:g}": _quantile(s, p) for p in ps}


def steady_quantiles(
    samples, skip_flags, ps=(0.5, 0.95, 0.99)
) -> tuple[dict[str, float], float, int]:
    """Quantiles over the samples NOT flagged in ``skip_flags`` — the
    serve family's steady-state latency report, where flagged rounds are
    the ones that triggered an XLA compile (cold-start skew, not serving
    jitter; a p95 of 3.2s against a p50 of 0.7s in the round-loop
    engine's artifact was pure compile noise).  Falls back to the full
    list when every sample is flagged (tiny drains).  Returns
    (quantile table, flagged_time, flagged_count)."""
    if len(samples) != len(skip_flags):
        raise ValueError(
            f"{len(samples)} samples vs {len(skip_flags)} skip flags"
        )
    kept = [s for s, skip in zip(samples, skip_flags) if not skip]
    skipped = [s for s, skip in zip(samples, skip_flags) if skip]
    return quantiles(kept or list(samples), ps), sum(skipped), len(skipped)


def summarize(values) -> dict:
    """Compact count/mean/max summary of a metric list — the artifact
    form of per-event series (the serve family's MTTR-in-rounds and
    replay-size reports).  Zeros when the list is empty, so a clean run
    and a chaos run share one schema."""
    vs = list(values)
    if not vs:
        return {"n": 0, "mean": 0.0, "max": 0}
    return {
        "n": len(vs),
        "mean": float(sum(vs)) / len(vs),
        "max": max(vs),
    }


def classify_outliers(samples: list[float]) -> dict:
    """Tukey-fence outlier classification (criterion's analysis: mild
    outside Q1/Q3 +- 1.5*IQR, severe outside +- 3*IQR — the capability the
    reference gets from the criterion crate, Cargo.toml:11).  Returns
    counts plus the flagged values so saved artifacts are self-auditing."""
    n = len(samples)
    if n < 4:
        return {"mild": 0, "severe": 0, "flagged": []}
    s = sorted(samples)
    q1, q3 = _quantile(s, 0.25), _quantile(s, 0.75)
    med = _quantile(s, 0.5)
    # Relative floor on the fence width: with tightly clustered samples
    # the raw IQR can be <0.1% of the median, and then ordinary timer
    # jitter lands outside 3*IQR and burns rerun rounds on benign
    # samples.  2% of the median keeps the Tukey shape while only
    # flagging deviations that could actually move a reported number.
    iqr = max(q3 - q1, 0.02 * abs(med))
    lo3, lo15 = q1 - 3.0 * iqr, q1 - 1.5 * iqr
    hi15, hi3 = q3 + 1.5 * iqr, q3 + 3.0 * iqr
    severe = [x for x in samples if x < lo3 or x > hi3]
    mild = [x for x in samples
            if (lo3 <= x < lo15) or (hi15 < x <= hi3)]
    out = {"mild": len(mild), "severe": len(severe),
           "flagged": sorted(mild + severe)}
    if severe or mild:
        out["fences"] = [lo3, lo15, hi15, hi3]
    return out


class SampleList(list):
    """The kept samples of one cell plus the harness's annotations:
    ``discarded`` = severe outliers that were re-measured and replaced
    (each re-run logged, never silently dropped), ``reruns`` = how many
    replacement rounds ran."""

    def __init__(self, xs=()):
        super().__init__(xs)
        self.discarded: list[float] = []
        self.reruns: int = 0


def measure(
    fn: Callable[[], object],
    *,
    warmup: int = 1,
    samples: int = 5,
    min_sample_time: float = 0.0,
    max_reruns: int = 2,
) -> SampleList:
    """Time ``fn`` ``samples`` times after ``warmup`` untimed calls.

    ``fn`` must be synchronous/blocking (device backends call
    ``block_until_ready`` internally — honest timing per SURVEY.md section 7
    hard-part 6).  If one call is shorter than ``min_sample_time``, loops
    within the sample and divides (criterion's iteration batching).

    Outlier policy (VERDICT r3 missing #1 — criterion's statistical
    rigor): after sampling, severe Tukey outliers (outside Q1/Q3 +-
    3*IQR; on this box they are environmental — a recompile, a tunnel
    stall, cpp running against a busy shared core) are re-measured up to
    ``max_reruns`` times; replaced values are kept in ``.discarded`` and
    persisted by BenchResult.to_dict, so a 12x-off sample can never sit
    unexplained in a committed artifact again.  Survivors after the
    rerun budget stay IN the sample set (annotated, not dropped)."""

    def one_sample() -> float:
        iters = 0
        t0 = time.perf_counter()
        while True:
            fn()
            iters += 1
            dt = time.perf_counter() - t0
            if dt >= min_sample_time:
                break
        return dt / iters

    for _ in range(warmup):
        fn()
    out = SampleList(one_sample() for _ in range(samples))
    for _ in range(max_reruns):
        cls = classify_outliers(out)
        if not cls["severe"]:
            break
        # fences come from the SAME classification that decided a rerun
        # is needed (severe > 0 guarantees they're present) — one Tukey
        # definition, no second copy of the formula to drift.
        lo3, hi3 = cls["fences"][0], cls["fences"][3]
        keep = SampleList(x for x in out if lo3 <= x <= hi3)
        keep.discarded = out.discarded + [
            x for x in out if x < lo3 or x > hi3
        ]
        keep.reruns = out.reruns + 1
        keep.extend(one_sample() for _ in range(samples - len(keep)))
        out = keep
    return out


# ---- persistence / baselines (criterion --save-baseline / --baseline) ----


def save_results(results: list[BenchResult], name: str = "latest",
                 results_dir: str = RESULTS_DIR) -> str:
    os.makedirs(results_dir, exist_ok=True)
    path = os.path.join(results_dir, f"{name}.json")
    with open(path, "w") as f:
        json.dump([r.to_dict() for r in results], f, indent=2)
    return path


def load_results(name: str, results_dir: str = RESULTS_DIR) -> dict[str, dict]:
    path = os.path.join(results_dir, f"{name}.json")
    with open(path) as f:
        return {d["group"] + "/" + d["trace"] + "/" + d["backend"]: d for d in json.load(f)}


def compare_to_baseline(
    results: list[BenchResult], baseline_name: str, results_dir: str = RESULTS_DIR
) -> list[str]:
    """Human-readable change report vs a saved baseline (criterion's
    regression comparison capability)."""
    base = load_results(baseline_name, results_dir)
    lines = []
    for r in results:
        b = base.get(r.bench_id)
        if not b:
            lines.append(f"{r.bench_id}: new")
            continue
        change = (r.median - b["median"]) / b["median"] * 100.0
        lines.append(
            f"{r.bench_id}: {r.median * 1e3:.2f}ms vs {b['median'] * 1e3:.2f}ms "
            f"({change:+.1f}%)"
        )
    return lines


def markdown_table(results: list[BenchResult]) -> str:
    """The bench table: one row per (group, trace), one column per backend
    (the 'tpu column next to the CPU rope baseline' of the north star)."""
    backends = sorted({r.backend for r in results})
    rows: dict[tuple[str, str], dict[str, BenchResult]] = {}
    for r in results:
        rows.setdefault((r.group, r.trace), {})[r.backend] = r
    out = ["| group | trace | " + " | ".join(backends) + " |"]
    out.append("|---" * (2 + len(backends)) + "|")
    for (group, trace), by_backend in sorted(rows.items()):
        cells = []
        for b in backends:
            r = by_backend.get(b)
            cells.append(f"{r.elements_per_sec:,.0f}/s" if r else "—")
        out.append(f"| {group} | {trace} | " + " | ".join(cells) + " |")
    return "\n".join(out)
