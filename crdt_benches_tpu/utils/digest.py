"""Order-sensitive document digests for convergence checking.

A cheap on-device fingerprint of the visible document (chars in order) that
replicas can compare via collectives without materializing content.  Replaces
the reference's length-only convergence oracle (reference src/main.rs:35,68)
with a content-sensitive check while staying collective-friendly.

Not cryptographic — two weighted sums in int32 (rank-weighted and
char-mixed), enough to make accidental collisions implausible for
convergence testing.  Byte-identical guarantees come from ``decode_state``
comparisons in tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_MIX = np.int32(-1640531527)  # 2654435761 as int32 (Knuth multiplicative)


def doc_digest(order: jax.Array, visible: jax.Array, length: jax.Array,
               chars: jax.Array) -> jax.Array:
    """Digest of the visible document in order.  Returns int32[3]:
    (rank-weighted char sum, mixed rolling component, visible length)."""
    C = order.shape[0]
    idx = jnp.arange(C, dtype=jnp.int32)
    valid = idx < length
    slot_at = jnp.where(valid, order, 0)
    vis = valid & visible[slot_at]
    rank = jnp.cumsum(vis.astype(jnp.int32))  # rank+1 at visible entries
    ch = jnp.where(vis, chars[slot_at], 0)
    h1 = jnp.sum(rank * (ch * _MIX + 1), where=vis, initial=0)
    h2 = jnp.sum((rank * rank) ^ (ch * 31 + rank), where=vis, initial=0)
    return jnp.stack([h1, h2, rank[-1]])


def doc_digest_packed(doc: jax.Array, length: jax.Array,
                      chars: jax.Array) -> jax.Array:
    """doc_digest over one replica's packed doc-order state
    (ops/apply2.py PackedState layout: ((slot+2)<<1)|vis, tombstones
    in-line).  Same digest value as doc_digest on the equivalent
    order/visible arrays."""
    C = doc.shape[0]
    idx = jnp.arange(C, dtype=jnp.int32)
    valid = idx < length
    slot = jnp.right_shift(doc, 1) - 2
    vis = valid & (jnp.bitwise_and(doc, 1) > 0)
    rank = jnp.cumsum(vis.astype(jnp.int32))
    ch = jnp.where(vis, chars[jnp.clip(slot, 0, chars.shape[0] - 1)], 0)
    h1 = jnp.sum(rank * (ch * _MIX + 1), where=vis, initial=0)
    h2 = jnp.sum((rank * rank) ^ (ch * 31 + rank), where=vis, initial=0)
    return jnp.stack([h1, h2, rank[-1]])
