"""Best-effort durability fsync helpers, in one place.

An ``os.replace``/``os.rename`` commits a NAME; the bytes behind it
(and the directory entry pointing at it) are only durable once fsynced
— the graftlint G018 contract.  These helpers are the single shared
implementation for every durable commit path (checkpoint saves, WAL
segment seals, GC manifests, snapshot barriers, flight dumps): a
future behavior change (O_DIRECTORY, EINTR retry, error surfacing)
lands once, not per-copy.

Stdlib-only on purpose: ``obs/flight.py`` must stay import-light for
its CLI validator, and ``utils/checkpoint.py`` pulls the whole engine
— so neither can be the shared home.

Best effort by contract: a filesystem that cannot open directories (or
rejects fsync on them) degrades to the pre-fix behavior, never to an
error.
"""

from __future__ import annotations

import os


def _fsync_path(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def fsync_file(path: str) -> None:
    """fsync an already-written FILE by path (snapshot barriers adopt
    hard-linked spool members whose hot-path writes skipped the
    per-eviction fsync — the barrier is where their contents must
    become durable, before the commit rename)."""
    _fsync_path(path)


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY: a rename is only durable once the directory
    entry itself is flushed — renaming into a never-synced directory
    can vanish with the page cache."""
    _fsync_path(path)
