"""Checkpoint / resume for replica document state.

The reference has no checkpoint subsystem (SURVEY.md section 5); its closest
analog is the update wire encoding (diamond-types ``encode_from``, reference
src/rope.rs:214).  The rebuild makes persistence first-class: any engine
state pytree (DocState, DownState, vmapped replica stacks) round-trips
through a single ``.npz`` file, so a long replay can stop after any op batch
and resume bit-exactly — tested in tests/test_checkpoint.py.

Format: one array per state field plus a field-order manifest and the state
class name; plain NumPy, no framework dependency on the read side.
"""

from __future__ import annotations

import numpy as np

from ..engine.downstream import DownPacked, DownState
from ..ops.apply import DocState
from ..ops.apply2 import PackedState, PackedState4, ReplayState

_CLASSES = {
    "DocState": DocState,
    "DownState": DownState,
    "ReplayState": ReplayState,
    "PackedState": PackedState,
    "PackedState4": PackedState4,
    "DownPacked": DownPacked,
}


def save_state(path: str, state) -> None:
    """Persist a DocState/DownState pytree (device arrays are fetched)."""
    cls = type(state).__name__
    if cls not in _CLASSES:
        raise TypeError(f"unsupported state type {cls}")
    arrays = {f: np.asarray(getattr(state, f)) for f in state._fields}
    np.savez_compressed(
        path, __class__=np.asarray(cls), __fields__=np.asarray(state._fields),
        **arrays,
    )


def load_state(path: str):
    """Restore a state pytree saved by :func:`save_state` (host arrays;
    device placement happens lazily on first use)."""
    with np.load(path) as z:
        cls = _CLASSES[str(z["__class__"])]
        fields = [str(f) for f in z["__fields__"]]
        return cls(**{f: z[f] for f in fields})
