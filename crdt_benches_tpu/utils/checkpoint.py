"""Checkpoint / resume for replica document state.

The reference has no checkpoint subsystem (SURVEY.md section 5); its closest
analog is the update wire encoding (diamond-types ``encode_from``, reference
src/rope.rs:214).  The rebuild makes persistence first-class: any engine
state pytree (DocState, DownState, vmapped replica stacks) round-trips
through a single ``.npz`` file, so a long replay can stop after any op batch
and resume bit-exactly — tested in tests/test_checkpoint.py.

Format: one array per state field plus a field-order manifest and the state
class name; plain NumPy, no framework dependency on the read side.
"""

from __future__ import annotations

import ml_dtypes
import numpy as np

_BF16 = np.dtype(ml_dtypes.bfloat16)

from ..engine.downstream import DownPacked, DownState
from ..ops.apply import DocState
from ..ops.apply2 import PackedState, PackedState4, ReplayState

_CLASSES = {
    "DocState": DocState,
    "DownState": DownState,
    "ReplayState": ReplayState,
    "PackedState": PackedState,
    "PackedState4": PackedState4,
    "DownPacked": DownPacked,
}


def save_state(path: str, state, compress: bool = True) -> None:
    """Persist a DocState/DownState pytree (device arrays are fetched).

    Non-NumPy-native dtypes need explicit handling: ``np.savez`` writes a
    bfloat16 array (PackedState4.cv_intile) but ``np.load`` reads it back
    as an opaque void dtype (``|V2``), silently breaking v4-state resume.
    Such fields are stored as a uint16 bit-view plus a dtype manifest and
    re-viewed on load.

    ``compress=False`` skips zlib (``np.savez``): the serve/ eviction
    spool writes thousands of small checkpoints per drain and the
    deflate pass dominated its host cost; ``load_state`` reads both
    forms transparently."""
    cls = type(state).__name__
    if cls not in _CLASSES:
        raise TypeError(f"unsupported state type {cls}")
    arrays = {}
    dtypes = []
    for f in state._fields:
        a = np.asarray(getattr(state, f))
        dtypes.append(str(a.dtype))
        if a.dtype == _BF16:
            a = a.view(np.uint16)
        arrays[f] = a
    saver = np.savez_compressed if compress else np.savez
    saver(
        path, __class__=np.asarray(cls), __fields__=np.asarray(state._fields),
        __dtypes__=np.asarray(dtypes), **arrays,
    )


def load_state(path: str):
    """Restore a state pytree saved by :func:`save_state` (host arrays;
    device placement happens lazily on first use)."""
    with np.load(path) as z:
        cls = _CLASSES[str(z["__class__"])]
        fields = [str(f) for f in z["__fields__"]]
        dtypes = (
            [str(d) for d in z["__dtypes__"]]
            if "__dtypes__" in z else [""] * len(fields)
        )
        out = {}
        for f, d in zip(fields, dtypes):
            a = z[f]
            if d == "bfloat16":
                a = a.view(_BF16)
            elif a.dtype.kind == "V":
                # A void field with no dtype manifest is a pre-manifest
                # checkpoint of a bf16-carrying state: unrecoverable
                # (np.savez dropped the dtype) — fail loudly here rather
                # than when jnp.asarray chokes far from the load site.
                raise ValueError(
                    f"checkpoint field {f!r} has opaque dtype {a.dtype}: "
                    "legacy checkpoint saved before the bfloat16 manifest "
                    "fix; re-create it with the current save_state"
                )
            out[f] = a
        return cls(**out)
