"""Checkpoint / resume for replica document state.

The reference has no checkpoint subsystem (SURVEY.md section 5); its closest
analog is the update wire encoding (diamond-types ``encode_from``, reference
src/rope.rs:214).  The rebuild makes persistence first-class: any engine
state pytree (DocState, DownState, vmapped replica stacks) round-trips
through a single ``.npz`` file, so a long replay can stop after any op batch
and resume bit-exactly — tested in tests/test_checkpoint.py.

Format: one array per state field plus a field-order manifest, the state
class name, and a per-array CRC32 manifest; plain NumPy, no framework
dependency on the read side.

Durability contract (the serve/ fleet leans on both properties):

- **atomic write**: :func:`save_state` writes to a same-directory temp
  file and ``os.replace``\\ s it over the target, so a crash (or injected
  exception) mid-write can never leave a torn ``.npz`` behind — the old
  file, if any, survives intact.  ``durable=True`` additionally fsyncs
  the staged bytes before the rename and the parent directory after it
  (graftlint G018: a committed rename must imply durable contents —
  snapshot members and manifests pass it; hot-path eviction spools do
  not, their loss is exactly what journal replay covers);
- **verified read**: :func:`load_state` checks every array against the
  saved CRC32 manifest and raises the typed
  :class:`CorruptCheckpointError` on any damage (truncation, bit flips,
  an unreadable zip) instead of surfacing a numpy decode crash far from
  the load site (graftlint G020's verify-before-trust reader).
  Pre-manifest checkpoints (no ``__crcs__`` field) load with
  verification skipped — the legacy fallback.

Both entry points are declared members of the ``spool`` durable
protocol (``# graftlint: durable=spool``): the static crash-consistency
rules check their effect sequences, and the runtime fs sanitizer
(``CRDT_BENCH_SANITIZE_FS=1``) attributes their fs ops — and can crash
them at every op boundary (serve/fscrash.py).
"""

from __future__ import annotations

import os
import tempfile
import zlib

import ml_dtypes
import numpy as np

_BF16 = np.dtype(ml_dtypes.bfloat16)

from ..engine.downstream import DownPacked, DownState
from ..lint.fs_sanitizer import fs_protocol
from ..ops.apply import DocState
from ..ops.apply2 import PackedState, PackedState4, ReplayState
from .fsdur import fsync_dir, fsync_file  # noqa: F401  (re-exported:
# journal.py and tests import the fsync helpers from here alongside
# save_state/load_state; the one implementation lives in utils/fsdur)

_CLASSES = {
    "DocState": DocState,
    "DownState": DownState,
    "ReplayState": ReplayState,
    "PackedState": PackedState,
    "PackedState4": PackedState4,
    "DownPacked": DownPacked,
}


class CorruptCheckpointError(ValueError):
    """A checkpoint failed integrity verification: torn/truncated file,
    CRC mismatch, or an undecodable archive.  Subclasses ValueError so
    pre-existing ``except ValueError`` callers keep working."""


def save_state(path: str, state, compress: bool = True,
               durable: bool = False) -> None:  # graftlint: durable=spool
    """Persist a DocState/DownState pytree (device arrays are fetched).

    Non-NumPy-native dtypes need explicit handling: ``np.savez`` writes a
    bfloat16 array (PackedState4.cv_intile) but ``np.load`` reads it back
    as an opaque void dtype (``|V2``), silently breaking v4-state resume.
    Such fields are stored as a uint16 bit-view plus a dtype manifest and
    re-viewed on load.

    ``compress=False`` skips zlib (``np.savez``): the serve/ eviction
    spool writes thousands of small checkpoints per drain and the
    deflate pass dominated its host cost; ``load_state`` reads both
    forms transparently.

    The write is ATOMIC: bytes land in a same-directory temp file that is
    ``os.replace``\\ d over ``path`` only once fully written, so an
    interrupted save (eviction killed mid-write, disk-full, crash) never
    leaves a torn file — and never destroys a previous good checkpoint
    at the same path.  ``durable=True`` makes the committed rename mean
    it: the staged file is fsynced before the replace and the parent
    directory after (the graftlint v4 audit fix — a rename alone can
    commit a name whose CONTENTS die with the page cache).  The default
    stays False on purpose: eviction spools are a rebuildable cache
    (deterministic streams + WAL replay), and snapshot barriers fsync
    the members they adopt, so the per-eviction hot path keeps its
    PR 2 cost profile."""
    cls = type(state).__name__
    if cls not in _CLASSES:
        raise TypeError(f"unsupported state type {cls}")
    arrays = {}
    dtypes = []
    crcs = []
    for f in state._fields:
        a = np.asarray(getattr(state, f))
        dtypes.append(str(a.dtype))
        if a.dtype == _BF16:
            a = a.view(np.uint16)
        arrays[f] = a
        crcs.append(zlib.crc32(np.ascontiguousarray(a).tobytes()))
    saver = np.savez_compressed if compress else np.savez
    d = os.path.dirname(os.path.abspath(path)) or "."
    with fs_protocol("spool"):
        fd, tmp = tempfile.mkstemp(
            dir=d, prefix=os.path.basename(path) + ".", suffix=".tmp"
        )
        try:
            # np.savez on a FILE OBJECT (a str path would get ".npz"
            # appended and orphan the temp file)
            with os.fdopen(fd, "wb") as fh:
                saver(
                    fh,
                    __class__=np.asarray(cls),
                    __fields__=np.asarray(state._fields),
                    __dtypes__=np.asarray(dtypes),
                    __crcs__=np.asarray(crcs, np.uint64),
                    **arrays,
                )
                if durable:
                    fh.flush()
                    os.fsync(fh.fileno())
            os.replace(tmp, path)
            if durable:
                fsync_dir(d)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise


def load_state(path: str, verify: bool = True):  # graftlint: durable=spool
    """Restore a state pytree saved by :func:`save_state` (host arrays;
    device placement happens lazily on first use).

    Every array is checked against the saved CRC32 manifest; any damage
    raises :class:`CorruptCheckpointError`.  Checkpoints written before
    the CRC manifest existed (no ``__crcs__`` field) load with the
    verification skipped — the legacy fallback."""
    try:
        with fs_protocol("spool"):
            z = np.load(path)
    except Exception as e:  # BadZipFile / OSError / EOFError / ValueError
        raise CorruptCheckpointError(
            f"checkpoint {path!r}: unreadable ({type(e).__name__}: {e})"
        ) from e
    with z:
        try:
            cls = _CLASSES[str(z["__class__"])]
            fields = [str(f) for f in z["__fields__"]]
            dtypes = (
                [str(d) for d in z["__dtypes__"]]
                if "__dtypes__" in z else [""] * len(fields)
            )
            crcs = z["__crcs__"] if "__crcs__" in z else None
            out = {}
            for i, (f, d) in enumerate(zip(fields, dtypes)):
                a = z[f]
                if verify and crcs is not None:
                    got = zlib.crc32(np.ascontiguousarray(a).tobytes())
                    if got != int(crcs[i]):
                        raise CorruptCheckpointError(
                            f"checkpoint {path!r}: field {f!r} CRC mismatch "
                            f"(stored {int(crcs[i]):#010x}, got {got:#010x})"
                        )
                if d == "bfloat16":
                    a = a.view(_BF16)
                elif a.dtype.kind == "V":
                    # A void field with no dtype manifest is a pre-manifest
                    # checkpoint of a bf16-carrying state: unrecoverable
                    # (np.savez dropped the dtype) — fail loudly here rather
                    # than when jnp.asarray chokes far from the load site.
                    raise CorruptCheckpointError(
                        f"checkpoint field {f!r} has opaque dtype {a.dtype}: "
                        "legacy checkpoint saved before the bfloat16 "
                        "manifest fix; re-create it with the current "
                        "save_state"
                    )
                out[f] = a
        except CorruptCheckpointError:
            raise
        except Exception as e:  # truncated zip member, missing key, ...
            raise CorruptCheckpointError(
                f"checkpoint {path!r}: damaged archive "
                f"({type(e).__name__}: {e})"
            ) from e
        return cls(**out)
