"""Headline benchmark — prints ONE JSON line.

Metric (BASELINE.json north star): aggregate CRDT replay throughput on the
automerge-paper trace, many replicas batched on one chip via the JAX engine,
in elements/sec (element = one trace patch, the reference's Criterion
throughput unit, reference src/main.rs:25).

vs_baseline = aggregate JAX throughput / single-core native C++ CRDT
throughput on the same trace (the reference's workload is a single-threaded
CRDT replay on one CPU core; our cpp-crdt treap engine is the local
stand-in since no reference numbers are published — BASELINE.md).

Environment knobs:
  CRDT_BENCH_TRACE     trace name (default automerge-paper)
  CRDT_BENCH_REPLICAS  replica count (default auto: 256 on TPU, 8 on CPU)
  CRDT_BENCH_SAMPLES   timed samples (default 5)
  CRDT_BENCH_BATCH     op batch size (default 1536; the coalesced range
                       engine peaks there on automerge-paper)
  CRDT_BENCH_PLATFORM  pin the JAX platform (e.g. "cpu"); if the accelerator
                       backend errors out, bench falls back to CPU anyway
"""

from __future__ import annotations

import json
import os
import sys


from statistics import median as _median  # noqa: E402
# Median sample time — matches the harness and recorded results (the
# headline must not get the most favorable of the samples).


def main() -> int:
    trace_name = os.environ.get("CRDT_BENCH_TRACE", "automerge-paper")
    samples = int(os.environ.get("CRDT_BENCH_SAMPLES", "5"))
    batch = int(os.environ.get("CRDT_BENCH_BATCH", "1536"))

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from crdt_benches_tpu.bench.harness import measure
    from crdt_benches_tpu.traces.loader import load_testing_data
    from crdt_benches_tpu.traces.patches import patch_arrays

    trace = load_testing_data(trace_name)
    elements = len(trace)
    end_len = len(trace.end_content)

    # ---- single-core native CRDT baselines (untimed setup, timed replay).
    # TWO cpp-crdt columns for stream symmetry (VERDICT r3 weak #4): the
    # per-patch stream (the reference's own calling shape, one replace per
    # patch, src/main.rs:31-32) and the RLE-coalesced stream — the SAME
    # stream the JAX range engine replays — so the headline ratio compares
    # identical inputs on both sides.  Throughput unit stays element =
    # trace patch for both (the same document work either way). ----
    baseline_eps = baseline_rle_eps = None
    try:
        from crdt_benches_tpu.backends.native import CppCrdt, native_available
        from crdt_benches_tpu.traces.tensorize import coalesce_patches

        if native_available():
            pa = patch_arrays(trace)

            def native_iter():
                assert CppCrdt.replay_patches(pa) == end_len

            times = measure(native_iter, warmup=1, samples=samples)
            baseline_eps = elements / _median(times)

            pa_rle = patch_arrays(
                trace, patches=list(coalesce_patches(trace))
            )

            def native_iter_rle():
                assert CppCrdt.replay_patches(pa_rle) == end_len

            times = measure(native_iter_rle, warmup=1, samples=samples)
            baseline_rle_eps = elements / _median(times)
    except Exception as e:  # baseline is advisory; the metric must still print
        print(f"native baseline failed: {e}", file=sys.stderr)

    # ---- JAX batched replay ----
    import jax

    if os.environ.get("CRDT_BENCH_PLATFORM"):
        # explicit platform pin (e.g. cpu when the TPU tunnel is busy);
        # config API because this env's sitecustomize overrides JAX_PLATFORMS
        jax.config.update("jax_platforms", os.environ["CRDT_BENCH_PLATFORM"])
    try:
        platform = jax.devices()[0].platform
    except RuntimeError as e:  # accelerator tunnel down -> still produce
        # the metric on CPU rather than failing the whole bench run
        print(
            f"warning: accelerator backend unavailable ({e}); "
            "falling back to CPU",
            file=sys.stderr,
        )
        jax.config.update("jax_platforms", "cpu")
        platform = jax.devices()[0].platform
    # 1024 replicas = the BASELINE.md config-4 shape (aggregate throughput
    # is flat from 128 up — per-replica O(C) work saturates the chip).
    default_r = 1024 if platform not in ("cpu",) else 8
    replicas = int(os.environ.get("CRDT_BENCH_REPLICAS", str(default_r)))

    from crdt_benches_tpu.backends.jax_backend import JaxReplayBackend

    backend = JaxReplayBackend(n_replicas=replicas, batch=batch)
    backend.prepare(trace)
    times = measure(backend.replay_once, warmup=1, samples=samples)
    agg_eps = elements * replicas / _median(times)

    # Headline ratio = stream-SYMMETRIC: the cpp baseline consumes the
    # same RLE-coalesced stream the JAX range engine replays.  The
    # per-patch-stream ratio (the reference's own calling shape, and the
    # r1-r3 headline denominator) rides along as vs_cpp_perpatch.  If the
    # RLE baseline failed to run, the label says which denominator was
    # actually used — never claim stream symmetry on a fallback.
    base = baseline_rle_eps or baseline_eps
    vs = agg_eps / base if base else 0.0
    base_desc = (
        "cpp-crdt 1 core, same coalesced stream"
        if baseline_rle_eps
        else "cpp-crdt 1 core, per-patch stream (RLE baseline unavailable)"
    )
    out = {
        "metric": (
            f"{trace_name} aggregate replay throughput, "
            f"{replicas} replicas, jax-{platform} "
            f"(baseline: {base_desc})"
        ),
        "value": round(agg_eps, 1),
        "unit": "elements/sec",
        "vs_baseline": round(vs, 3),
    }
    if baseline_eps:
        out["vs_cpp_perpatch"] = round(agg_eps / baseline_eps, 3)
    if baseline_rle_eps:
        out["cpp_rle_els_per_sec"] = round(baseline_rle_eps, 1)
    if baseline_eps:
        out["cpp_perpatch_els_per_sec"] = round(baseline_eps, 1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
