"""Headline benchmark — prints ONE JSON line.

Metric (BASELINE.json north star): aggregate CRDT replay throughput on the
automerge-paper trace, many replicas batched on one chip via the JAX engine,
in elements/sec (element = one trace patch, the reference's Criterion
throughput unit, reference src/main.rs:25).

vs_baseline = aggregate JAX throughput / single-core native C++ CRDT
throughput on the same trace (the reference's workload is a single-threaded
CRDT replay on one CPU core; our cpp-crdt treap engine is the local
stand-in since no reference numbers are published — BASELINE.md).

Environment knobs:
  CRDT_BENCH_TRACE     trace name (default automerge-paper)
  CRDT_BENCH_REPLICAS  replica count (default auto: 256 on TPU, 8 on CPU)
  CRDT_BENCH_SAMPLES   timed samples (default 5)
  CRDT_BENCH_BATCH     op batch size (default 1536; the coalesced range
                       engine peaks there on automerge-paper)
  CRDT_BENCH_PLATFORM  pin the JAX platform (e.g. "cpu"); if the accelerator
                       backend errors out, bench falls back to CPU anyway
"""

from __future__ import annotations

import json
import os
import sys


from statistics import median as _median  # noqa: E402
# Median sample time — matches the harness and recorded results (the
# headline must not get the most favorable of the samples).


def main() -> int:
    trace_name = os.environ.get("CRDT_BENCH_TRACE", "automerge-paper")
    samples = int(os.environ.get("CRDT_BENCH_SAMPLES", "5"))
    batch = int(os.environ.get("CRDT_BENCH_BATCH", "1536"))

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from crdt_benches_tpu.bench.harness import measure
    from crdt_benches_tpu.traces.loader import load_testing_data
    from crdt_benches_tpu.traces.patches import patch_arrays

    trace = load_testing_data(trace_name)
    elements = len(trace)
    end_len = len(trace.end_content)

    # ---- single-core native CRDT baseline (untimed setup, timed replay) ----
    baseline_eps = None
    try:
        from crdt_benches_tpu.backends.native import CppCrdt, native_available

        if native_available():
            pa = patch_arrays(trace)

            def native_iter():
                assert CppCrdt.replay_patches(pa) == end_len

            times = measure(native_iter, warmup=1, samples=samples)
            baseline_eps = elements / _median(times)
    except Exception as e:  # baseline is advisory; the metric must still print
        print(f"native baseline failed: {e}", file=sys.stderr)

    # ---- JAX batched replay ----
    import jax

    if os.environ.get("CRDT_BENCH_PLATFORM"):
        # explicit platform pin (e.g. cpu when the TPU tunnel is busy);
        # config API because this env's sitecustomize overrides JAX_PLATFORMS
        jax.config.update("jax_platforms", os.environ["CRDT_BENCH_PLATFORM"])
    try:
        platform = jax.devices()[0].platform
    except RuntimeError as e:  # accelerator tunnel down -> still produce
        # the metric on CPU rather than failing the whole bench run
        print(
            f"warning: accelerator backend unavailable ({e}); "
            "falling back to CPU",
            file=sys.stderr,
        )
        jax.config.update("jax_platforms", "cpu")
        platform = jax.devices()[0].platform
    # 1024 replicas = the BASELINE.md config-4 shape (aggregate throughput
    # is flat from 128 up — per-replica O(C) work saturates the chip).
    default_r = 1024 if platform not in ("cpu",) else 8
    replicas = int(os.environ.get("CRDT_BENCH_REPLICAS", str(default_r)))

    from crdt_benches_tpu.backends.jax_backend import JaxReplayBackend

    backend = JaxReplayBackend(n_replicas=replicas, batch=batch)
    backend.prepare(trace)
    times = measure(backend.replay_once, warmup=1, samples=samples)
    agg_eps = elements * replicas / _median(times)

    vs = agg_eps / baseline_eps if baseline_eps else 0.0
    print(
        json.dumps(
            {
                "metric": (
                    f"{trace_name} aggregate replay throughput, "
                    f"{replicas} replicas, jax-{platform} "
                    f"(baseline: cpp-crdt 1 core)"
                ),
                "value": round(agg_eps, 1),
                "unit": "elements/sec",
                "vs_baseline": round(vs, 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
